"""TPU accelerator layer tests (mocked metadata — no TPU needed).

Reference test model: ``python/ray/tests/accelerators/test_tpu.py``."""

import os

import pytest

from ray_tpu.accelerators import (
    TPUAcceleratorManager,
    detect_node_accelerators,
    pod_type_chips_per_host,
    pod_type_num_chips,
    pod_type_num_hosts,
    set_metadata_fetcher,
    slice_head_resource_name,
)
from ray_tpu.accelerators.tpu import (
    ACCELERATOR_TYPE_OVERRIDE_ENV,
    NUM_CHIPS_OVERRIDE_ENV,
    TPU_VISIBLE_CHIPS_ENV,
    WORKER_ID_OVERRIDE_ENV,
)


@pytest.fixture(autouse=True)
def clean_env(monkeypatch):
    for var in (
        NUM_CHIPS_OVERRIDE_ENV,
        ACCELERATOR_TYPE_OVERRIDE_ENV,
        WORKER_ID_OVERRIDE_ENV,
        TPU_VISIBLE_CHIPS_ENV,
        "TPU_WORKER_HOSTNAMES",
        "TPU_NAME",
    ):
        monkeypatch.delenv(var, raising=False)
    set_metadata_fetcher(lambda path: None)  # no metadata server in CI
    yield
    set_metadata_fetcher(None)


def test_pod_type_math():
    # v2-v5p suffixes count TensorCores (2/chip); v5e/v6e count chips.
    assert pod_type_num_chips("v4-8") == 4
    assert pod_type_num_chips("v4-32") == 16
    assert pod_type_num_chips("v5litepod-16") == 16
    assert pod_type_chips_per_host("v4-32") == 4
    assert pod_type_chips_per_host("v5litepod-16") == 8
    assert pod_type_num_hosts("v4-8") == 1
    assert pod_type_num_hosts("v4-32") == 4
    assert pod_type_num_hosts("v5litepod-16") == 2
    assert slice_head_resource_name("v4-32") == "TPU-v4-32-head"


def test_detect_via_env_override(monkeypatch):
    monkeypatch.setenv(NUM_CHIPS_OVERRIDE_ENV, "4")
    assert TPUAcceleratorManager.get_current_node_num_accelerators() == 4
    resources, labels = detect_node_accelerators()
    assert resources["TPU"] == 4.0


def test_detect_via_metadata(monkeypatch):
    meta = {
        "attributes/accelerator-type": "v4-16",
        "attributes/agent-worker-number": "0",
        "attributes/instance-id": "my-tpu-pod",
    }
    set_metadata_fetcher(meta.get)
    assert TPUAcceleratorManager.get_current_node_tpu_pod_type() == "v4-16"
    assert TPUAcceleratorManager.get_current_node_accelerator_type() == "TPU-V4"
    assert TPUAcceleratorManager.get_current_node_tpu_worker_id() == 0
    # no /dev/accel* in CI → falls back to pod-type arithmetic (4/host)
    assert TPUAcceleratorManager.get_current_node_num_accelerators() == 4
    resources, labels = detect_node_accelerators()
    assert resources["TPU"] == 4.0
    assert resources[slice_head_resource_name("v4-16")] == 1.0
    assert labels["ray.io/accelerator-type"] == "TPU-V4"
    assert labels["ray.io/tpu-pod-name"] == "my-tpu-pod"


def test_head_resource_only_on_worker_zero(monkeypatch):
    meta = {"attributes/accelerator-type": "v4-32"}
    set_metadata_fetcher(meta.get)
    monkeypatch.setenv(WORKER_ID_OVERRIDE_ENV, "1")
    extras = TPUAcceleratorManager.get_additional_node_resources()
    assert slice_head_resource_name("v4-32") not in extras
    monkeypatch.setenv(WORKER_ID_OVERRIDE_ENV, "0")
    extras = TPUAcceleratorManager.get_additional_node_resources()
    assert extras[slice_head_resource_name("v4-32")] == 1.0


def test_visible_chips_isolation(monkeypatch):
    TPUAcceleratorManager.set_current_process_visible_accelerator_ids(["0", "1"])
    assert os.environ[TPU_VISIBLE_CHIPS_ENV] == "0,1"
    assert TPUAcceleratorManager.get_current_process_visible_accelerator_ids() == ["0", "1"]
    # 2 chips → libtpu bounds hints set
    assert os.environ["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,2,1"
    TPUAcceleratorManager.set_current_process_visible_accelerator_ids(["0", "1", "2", "3"])
    assert "TPU_CHIPS_PER_PROCESS_BOUNDS" not in os.environ


def test_validate_request():
    ok, _ = TPUAcceleratorManager.validate_resource_request_quantity(2)
    assert ok
    ok, msg = TPUAcceleratorManager.validate_resource_request_quantity(3)
    assert not ok and "chips" in msg
    ok, _ = TPUAcceleratorManager.validate_resource_request_quantity(8)
    assert ok  # whole hosts
    ok, msg = TPUAcceleratorManager.validate_resource_request_quantity(0.5)
    assert not ok


def test_worker_count(monkeypatch):
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h0,h1,h2,h3")
    assert TPUAcceleratorManager.get_num_workers_in_current_tpu_pod() == 4
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES")
    monkeypatch.setenv(ACCELERATOR_TYPE_OVERRIDE_ENV, "v4-32")
    assert TPUAcceleratorManager.get_num_workers_in_current_tpu_pod() == 4


def test_daemon_chip_pool_allocation(tmp_path):
    """Daemon assigns disjoint chip ids to dedicated TPU actor workers."""
    from ray_tpu.core.node_daemon import NodeDaemon

    daemon = NodeDaemon.__new__(NodeDaemon)
    daemon._tpu_chips_free = [0, 1, 2, 3]
    a = daemon._allocate_tpu_chips(2)
    b = daemon._allocate_tpu_chips(2)
    assert a == [0, 1] and b == [2, 3]
    assert daemon._allocate_tpu_chips(1) is None  # exhausted
    daemon._free_tpu_chips(a)
    assert daemon._allocate_tpu_chips(2) == [0, 1]
