"""Flash attention kernel vs. reference (interpret mode on CPU)."""

import numpy as np
import pytest

from ray_tpu.ops.attention import flash_attention, reference_attention


def make_qkv(b=1, h=2, s=256, d=64, seed=0, dtype="float32"):
    import jax.numpy as jnp

    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, h, s, d), dtype) * 0.3
    k = jnp.asarray(rng.randn(b, h, s, d), dtype) * 0.3
    v = jnp.asarray(rng.randn(b, h, s, d), dtype) * 0.3
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_flash_forward_matches_reference(causal):
    q, k, v = make_qkv(s=256)
    out = flash_attention(q, k, v, causal=causal, impl="pallas", block_q=128, block_k=128)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_flash_backward_matches_reference():
    import jax
    import jax.numpy as jnp

    q, k, v = make_qkv(s=256)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, impl="pallas") ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-3, atol=3e-3)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_gqa_in_kernel_head_mapping(causal):
    """GQA: k/v at n_kv_heads < n_heads must match the repeated-KV
    reference — the kernel maps q-head -> kv-head in its index map."""
    import jax
    import jax.numpy as jnp

    q, _, _ = make_qkv(b=2, h=4, s=256, d=64, seed=1)
    _, k, v = make_qkv(b=2, h=2, s=256, d=64, seed=2)
    out = flash_attention(q, k, v, causal=causal, impl="pallas", block_q=128, block_k=128)
    kr = jnp.repeat(k, 2, axis=1)
    vr = jnp.repeat(v, 2, axis=1)
    ref = reference_attention(q, kr, vr, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, impl="pallas", block_q=128, block_k=128) ** 2)

    def loss_ref(q, k, v):
        kr = jnp.repeat(k, 2, axis=1)
        vr = jnp.repeat(v, 2, axis=1)
        return jnp.sum(reference_attention(q, kr, vr, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-3, atol=3e-3)


def test_flash_branched_mask_path():
    """>=8 K tiles triggers the lax.cond diagonal-branch mask path in
    all three kernels (fwd, bwd_dq, bwd_dkv) — CI must not leave it to
    be discovered on TPU at s>=1024."""
    import jax
    import jax.numpy as jnp

    q, k, v = make_qkv(h=1, s=1024, d=64, seed=4)
    out = flash_attention(q, k, v, causal=True, impl="pallas", block_q=128, block_k=128)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, impl="pallas", block_q=128, block_k=128) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-3, atol=3e-3)


def test_uneven_seq_block_fallback():
    """Sequences not divisible by the requested block fall back to a
    divisor block (or the sequence itself) instead of erroring."""
    import numpy as np

    q, k, v = make_qkv(s=200)
    out = flash_attention(q, k, v, impl="pallas", block_q=128, block_k=128)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_layers():
    import jax.numpy as jnp

    from ray_tpu.ops.layers import apply_rope, rms_norm, rope_frequencies

    x = jnp.ones((2, 8), jnp.float32) * 3
    w = jnp.ones((8,))
    out = rms_norm(x, w)
    np.testing.assert_allclose(np.asarray(out), np.ones((2, 8)), rtol=1e-5)

    cos, sin = rope_frequencies(64, 128)
    assert cos.shape == (128, 32)
    xq = jnp.ones((1, 2, 16, 64))
    rotated = apply_rope(xq, cos, sin)
    assert rotated.shape == xq.shape
    # norm preserved by rotation
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(rotated), axis=-1),
        np.linalg.norm(np.asarray(xq), axis=-1),
        rtol=1e-5,
    )
