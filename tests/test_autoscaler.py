"""Autoscaler: demand-driven scale-up on the fake provider, idle
scale-down, and atomic TPU-slice launches.

Reference test model: ``python/ray/tests/test_autoscaler_fake_multinode.py``
on ``FakeMultiNodeProvider`` (``fake_multi_node/node_provider.py:236``).

One MODULE-scoped cluster serves every test (boot/teardown was ~3x the
module's actual test time); each test builds its own ``StandardAutoscaler``
against the shared provider, and an autouse fixture reaps any autoscaled
nodes a test leaves behind and waits for the controller to notice — the
exact-node-count assertions below depend on starting from a bare head.
"""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import (
    AutoscalerConfig,
    FakeMultiNodeProvider,
    NodeTypeConfig,
    StandardAutoscaler,
)
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def small_cluster():
    from ray_tpu.core.config import GLOBAL_CONFIG

    # Deflake (round-5 verdict: order/timing-flaky on a loaded 1-vCPU
    # box): autoscaled node boot can exceed the default 30s infeasible
    # patience when the suite has the machine saturated — the task then
    # fails terminally moments before its node joins. Raise the patience
    # BEFORE Cluster() so it serializes into every spawned process too.
    old_patience = GLOBAL_CONFIG.infeasible_fail_after_s
    GLOBAL_CONFIG.infeasible_fail_after_s = 90.0
    cluster = Cluster(num_cpus=1)
    ray_tpu.init(address=cluster.address)
    provider = FakeMultiNodeProvider(f"127.0.0.1:{cluster.controller_port}")
    yield cluster, provider
    GLOBAL_CONFIG.infeasible_fail_after_s = old_patience  # before any teardown raise
    try:
        provider.shutdown()
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


@pytest.fixture(autouse=True)
def _reap_leftover_nodes(small_cluster):
    """Module-scoped-cluster hygiene: terminate every autoscaled node a
    test left running (e.g. the demand test ends inside its 30s idle
    window) and wait until the controller agrees only the head is alive
    — otherwise a stale 4-CPU node record absorbs the next test's
    demand probe and its exact provider-node-count assertions drift."""
    _cluster, provider = small_cluster
    yield
    for rec in provider.non_terminated_nodes():
        provider.terminate_node(rec["id"])
    _wait(
        lambda: sum(1 for n in ray_tpu.nodes() if n["Alive"]) == 1,
        timeout=60,
        msg="leftover autoscaled nodes should leave the cluster",
    )


def _wait(pred, timeout=60, msg=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.25)
    raise AssertionError(f"timed out: {msg}")


def test_scale_up_schedule_and_idle_terminate(small_cluster):
    """Infeasible-now work launches a fake node, the work schedules on
    it, and the node terminates once idle past the timeout."""
    _cluster, provider = small_cluster
    autoscaler = StandardAutoscaler(
        provider,
        AutoscalerConfig(
            node_types=[NodeTypeConfig("worker", {"CPU": 4}, max_workers=2)],
            idle_timeout_s=2.0,
            update_interval_s=0.3,
        ),
    )
    autoscaler.start()
    try:

        @ray_tpu.remote(num_cpus=4)
        class Big:
            def ping(self):
                return "pong"

        # head has 1 CPU: this actor is unschedulable until a node appears
        a = Big.remote()
        assert ray_tpu.get(a.ping.remote(), timeout=90) == "pong"
        assert len(provider.non_terminated_nodes()) >= 1

        # drop the actor: its node should go idle and be terminated
        del a
        _wait(
            lambda: len(provider.non_terminated_nodes()) == 0,
            timeout=60,
            msg="idle node should terminate",
        )
    finally:
        autoscaler.stop()


def test_task_demand_scales_up(small_cluster):
    """Parked lease requests (queued tasks) also count as demand."""
    _cluster, provider = small_cluster
    autoscaler = StandardAutoscaler(
        provider,
        AutoscalerConfig(
            node_types=[NodeTypeConfig("worker", {"CPU": 4}, max_workers=1)],
            idle_timeout_s=30.0,
            update_interval_s=0.3,
        ),
    )
    autoscaler.start()
    try:

        @ray_tpu.remote(num_cpus=3)
        def heavy():
            return 42

        assert ray_tpu.get(heavy.remote(), timeout=90) == 42
        assert len(provider.non_terminated_nodes()) == 1
    finally:
        autoscaler.stop()


def test_tpu_slice_launches_atomically(small_cluster):
    """A slice node type (hosts=2) launches both hosts in one scaling
    decision — TPU slices are indivisible units."""
    _cluster, provider = small_cluster
    autoscaler = StandardAutoscaler(
        provider,
        AutoscalerConfig(
            node_types=[
                NodeTypeConfig(
                    "v5e-slice", {"CPU": 1, "FAKETPU": 4}, max_workers=1, hosts=2
                )
            ],
            idle_timeout_s=60.0,
            update_interval_s=0.3,
        ),
    )
    autoscaler.start()
    try:

        @ray_tpu.remote(num_cpus=0, resources={"FAKETPU": 4})
        def on_slice():
            return "ok"

        assert ray_tpu.get(on_slice.remote(), timeout=120) == "ok"
        # both hosts exist as provider records the moment the single
        # create_node returns — but assert with a grace window rather
        # than instantaneously (the second host's spawn can still be
        # mid-boot on a saturated box, and an autoscaler pass may be
        # in flight)
        _wait(
            lambda: len(provider.non_terminated_nodes()) == 2,
            timeout=30,
            msg=f"atomic slice launch: {provider.non_terminated_nodes()}",
        )
        assert len(provider.non_terminated_nodes()) == 2  # and never more
        _wait(
            lambda: sum(
                1 for n in ray_tpu.nodes() if n["Alive"]
            ) >= 3,
            timeout=60,
            msg="both slice hosts join the cluster",
        )
    finally:
        autoscaler.stop()
