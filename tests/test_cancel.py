"""Task cancellation (reference ``CoreWorker::CancelTask``) + the
event-driven wait path."""

import time

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=2)
    yield
    ray_tpu.shutdown()


def test_cancel_queued_task(cluster):
    """Tasks still waiting for a lease fail fast with TaskCancelledError."""

    @ray_tpu.remote(num_cpus=1)
    def hog():
        # long enough that the victim is still lease-parked when the
        # cancel lands (0.5s in) — 5s keeps slack without burning wall
        time.sleep(5)
        return "done"

    @ray_tpu.remote(num_cpus=1)
    def queued():
        return "ran"

    hogs = [hog.remote() for _ in range(2)]  # occupy both CPUs
    time.sleep(0.5)
    victim = queued.remote()  # stuck waiting for a lease
    ray_tpu.cancel(victim)
    with pytest.raises(ray_tpu.TaskCancelledError):
        ray_tpu.get(victim, timeout=30)
    assert ray_tpu.get(hogs, timeout=60) == ["done", "done"]


def test_cancel_running_task_cooperative(cluster):
    """A running pure-Python loop gets TaskCancelledError raised in its
    execution thread."""

    @ray_tpu.remote(num_cpus=1)
    def spin():
        t0 = time.time()
        while time.time() - t0 < 30:
            time.sleep(0.01)  # bytecode boundary for the async exception
        return "survived"

    ref = spin.remote()
    time.sleep(1.5)  # let it start executing
    ray_tpu.cancel(ref)
    t0 = time.time()
    with pytest.raises(ray_tpu.TaskCancelledError):
        ray_tpu.get(ref, timeout=60)
    assert time.time() - t0 < 20  # cancelled, not run to completion


def test_cancel_running_task_force(cluster):
    """force=True kills the executing worker process."""

    @ray_tpu.remote(num_cpus=1)
    def stuck():
        time.sleep(60)
        return "survived"

    ref = stuck.remote()
    time.sleep(1.5)
    ray_tpu.cancel(ref, force=True)
    t0 = time.time()
    with pytest.raises(ray_tpu.TaskCancelledError):
        ray_tpu.get(ref, timeout=60)
    assert time.time() - t0 < 20


def test_cancel_put_ref_rejected(cluster):
    ref = ray_tpu.put(123)
    with pytest.raises(ValueError):
        ray_tpu.cancel(ref)


def test_cancel_finished_task_noop(cluster):
    @ray_tpu.remote
    def quick():
        return 7

    ref = quick.remote()
    assert ray_tpu.get(ref, timeout=60) == 7
    ray_tpu.cancel(ref)  # no-op
    assert ray_tpu.get(ref, timeout=60) == 7


def test_wait_wakes_promptly(cluster):
    """Event-driven wait: completion wakes the waiter without polling
    delay; unfinished refs stay not-ready."""

    @ray_tpu.remote
    def fast():
        return 1

    @ray_tpu.remote(num_cpus=0)
    def slow():
        time.sleep(10)
        return 2

    s = slow.remote()
    f = fast.remote()
    ready, not_ready = ray_tpu.wait([s, f], num_returns=1, timeout=30)
    assert ready == [f] and not_ready == [s]
    # timeout path: nothing ready
    ready2, not_ready2 = ray_tpu.wait([s], num_returns=1, timeout=0.2)
    assert ready2 == [] and not_ready2 == [s]


def test_cancel_borrowed_ref_forwards_to_owner(cluster):
    """A borrower (actor) cancelling a driver-owned task forwards the
    cancel to the owner (reference CancelTask owner routing)."""

    @ray_tpu.remote(num_cpus=1)
    def spin():
        t0 = time.time()
        while time.time() - t0 < 30:
            time.sleep(0.01)
        return "survived"

    @ray_tpu.remote(num_cpus=0)
    class Canceller:
        def cancel_it(self, refs):
            ray_tpu.cancel(refs[0])
            return True

    ref = spin.remote()
    time.sleep(1.0)
    c = Canceller.remote()
    assert ray_tpu.get(c.cancel_it.remote([ref]), timeout=30)
    t0 = time.time()
    with pytest.raises(ray_tpu.TaskCancelledError):
        ray_tpu.get(ref, timeout=60)
    assert time.time() - t0 < 20
