"""Chaos tests: workloads must complete CORRECTLY while killers take
out workers/nodes at random (reference ``_private/test_utils.py:1496``
killer actors + ``tests/chaos/``). RPC-level chaos (env-configured
``testing_rpc_failure``) is layered onto the cluster fixture so every
retried control-plane RPC path also gets exercised.
"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.chaos import NodeKiller, WorkerKiller, find_worker_pids


@pytest.fixture(scope="module")
def chaos_cluster():
    # inject retryable RPC failures into every daemon/worker the cluster
    # spawns (subprocess env inherits): 8% of task/actor pushes fail
    # with a transient (ChaosInjectedError) the submitters must retry.
    # Module-scoped (suite wall-time): the chaos tests tolerate — are
    # BUILT for — killed workers, so sharing one cluster is safe.
    import os as _os

    _os.environ["RAY_TPU_testing_rpc_failure"] = "push_batch:0.08"
    cluster = None
    try:
        cluster = Cluster(num_cpus=2)
        ray_tpu.init(address=cluster.address)
        yield cluster
    finally:
        ray_tpu.shutdown()
        if cluster is not None:
            cluster.shutdown()
        from ray_tpu.core.config import GLOBAL_CONFIG

        _os.environ.pop("RAY_TPU_testing_rpc_failure", None)
        GLOBAL_CONFIG.reset()


def _controller_addr(cluster: Cluster) -> str:
    return f"127.0.0.1:{cluster.controller_port}"


def test_lineage_task_graph_under_worker_chaos(chaos_cluster):
    """A dependency graph of retryable tasks completes with the right
    answer while a killer SIGKILLs workers (task retries + lineage
    reconstruction of lost intermediate objects)."""

    @ray_tpu.remote(max_retries=5, num_cpus=0.5)
    def square(x):
        time.sleep(0.05)
        return x * x

    @ray_tpu.remote(max_retries=5, num_cpus=0.5)
    def add(a, b):
        time.sleep(0.05)
        return a + b

    killer = WorkerKiller(
        _controller_addr(chaos_cluster), interval_s=0.7, max_kills=6, seed=1
    ).start()
    try:
        # two fan-in layers: leaf results feed sums (lineage deps)
        leaves = [square.remote(i) for i in range(12)]
        sums = [add.remote(leaves[i], leaves[i + 1]) for i in range(0, 12, 2)]
        total = ray_tpu.get(
            [add.remote(sums[i], sums[i + 1]) for i in range(0, 6, 2)],
            timeout=240,
        )
    finally:
        kills = killer.stop()
    expect = [sum(j * j for j in range(k, k + 4)) for k in range(0, 12, 4)]
    assert total == expect, (total, expect)
    assert kills, "killer never fired — chaos was a no-op"


def test_actor_workload_under_worker_chaos(chaos_cluster):
    """Restartable actors keep answering correctly while their worker
    processes are SIGKILLed (actor-restart FSM + task retries)."""

    @ray_tpu.remote(max_restarts=-1, max_task_retries=8, num_cpus=0.5)
    class Counter:
        def __init__(self):
            self.mine = 0

        def bump(self, x):
            time.sleep(0.03)
            self.mine += 1
            return x * 2

    actors = [Counter.remote() for _ in range(2)]
    # warm them up so the killer has targets
    ray_tpu.get([a.bump.remote(0) for a in actors], timeout=120)
    killer = WorkerKiller(
        _controller_addr(chaos_cluster), interval_s=0.8, max_kills=5, seed=2
    ).start()
    try:
        results = []
        for i in range(30):
            results.append(
                ray_tpu.get(actors[i % 2].bump.remote(i), timeout=180)
            )
    finally:
        kills = killer.stop()
    assert results == [i * 2 for i in range(30)]
    assert kills, "killer never fired — chaos was a no-op"


def test_find_worker_pids_scopes_to_cluster(chaos_cluster):
    """The pid scanner must only see THIS cluster's workers."""

    @ray_tpu.remote(num_cpus=0.5)
    def touch():
        return os.getpid()

    pid = ray_tpu.get(touch.remote(), timeout=120)
    pids = find_worker_pids(_controller_addr(chaos_cluster))
    assert pid in pids
    assert find_worker_pids("127.0.0.1:1") == []


# slow: the in-gate equivalent is test_drain.py::
# test_preemption_mid_training_resumes_from_urgent_checkpoint (same
# restart-from-checkpoint path, plus the drain protocol on top)
@pytest.mark.slow
def test_trainer_completes_under_node_chaos():
    """JaxTrainer + FailureConfig: training restarts from the latest
    checkpoint when the node hosting a train worker dies mid-run, and
    still converges (reference: Train fault tolerance =
    restart-worker-group-from-checkpoint)."""
    # last in the module by construction: the module-scoped chaos_cluster
    # fixture (used by every other test here) stays alive until module
    # teardown, and this test needs its own fresh cluster — disconnect
    # the fixture's driver first (shutdown is idempotent at teardown)
    ray_tpu.shutdown()
    cluster = Cluster(num_cpus=1)
    cluster.add_node(num_cpus=2, resources={"trainer": 2})
    time.sleep(1.0)
    ray_tpu.init(address=cluster.address)
    try:
        from ray_tpu.train import (
            FailureConfig,
            JaxTrainer,
            RunConfig,
            ScalingConfig,
        )
        from ray_tpu import train

        def train_fn(config):
            w = 0.0
            start = 0
            ckpt = train.get_checkpoint()
            if ckpt is not None:
                state = ckpt.to_dict()
                w, start = state["w"], state["step"]
            for step in range(start, 12):
                time.sleep(0.4)
                w += 1.0
                train.report(
                    {"w": w, "step": step + 1},
                    checkpoint=train.Checkpoint.from_dict(
                        {"w": w, "step": step + 1}
                    ),
                )
            # a restart can resume AT step 12 (killed after the final
            # checkpoint): the loop is empty, so report final state
            # unconditionally or the run ends metric-less
            train.report({"w": w, "step": 12})

        trainer = JaxTrainer(
            train_fn,
            train_loop_config={},
            scaling_config=ScalingConfig(
                num_workers=1,
                resources_per_worker={"CPU": 1, "trainer": 1},
            ),
            run_config=RunConfig(
                # unique name: a fixed one resumes a PRIOR test run's
                # persisted checkpoint and finishes before the killer fires
                name=f"chaos-train-{os.getpid()}-{int(time.time()*1000)}",
                failure_config=FailureConfig(max_failures=4),
            ),
        )
        killer = NodeKiller(
            cluster,
            interval_s=2.0,
            replace=True,
            node_resources={"trainer": 2},
            num_cpus=2,
            max_kills=1,
            seed=3,
        ).start()
        try:
            result = trainer.fit()
        finally:
            kills = killer.stop()
        assert result.metrics["w"] == 12.0
        assert kills >= 1, "node killer never fired"
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
