"""Operator CLI (reference ``ray start/stop/status/list``,
``scripts/scripts.py``)."""

import json
import os
import re
import subprocess
import sys
import time

import pytest

ENV = dict(os.environ, PYTHONPATH=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _cli(*args, timeout=120):
    return subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts", *args],
        capture_output=True, text=True, env=ENV, timeout=timeout,
    )


def test_cli_start_status_list_stop(tmp_path):
    out = _cli("start", "--head", "--num-cpus", "2",
               "--session-dir", str(tmp_path / "sess"))
    assert out.returncode == 0, out.stderr
    addr = re.search(r"address: (\S+)", out.stdout).group(1)
    try:
        out2 = _cli("start", "--address", addr, "--num-cpus", "1")
        assert out2.returncode == 0, out2.stderr
        time.sleep(2)

        st = _cli("status", "--address", addr)
        assert st.returncode == 0, st.stderr
        assert "cluster: 2 node(s)" in st.stdout
        assert "CPU" in st.stdout

        ls = _cli("list", "nodes", "--address", addr)
        assert ls.returncode == 0, ls.stderr
        rows = json.loads(ls.stdout)
        assert len(rows) == 2
    finally:
        stop = _cli("stop")
        assert "stopped" in stop.stdout
