"""Cluster-mode (real multiprocess runtime) tests.

Module-scoped cluster (reference pattern: shared ``ray_start_regular``
fixtures) to amortize startup on slow CI machines.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core.exceptions import ActorDiedError, TaskError


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_task_roundtrip(cluster):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(1, 2), timeout=60) == 3


def test_parallel_tasks(cluster):
    @ray_tpu.remote
    def sq(x):
        return x * x

    refs = [sq.remote(i) for i in range(8)]
    assert ray_tpu.get(refs, timeout=120) == [i * i for i in range(8)]


def test_large_object_via_shm(cluster):
    arr = np.random.rand(400, 400)  # ~1.2MB > inline threshold
    ref = ray_tpu.put(arr)
    np.testing.assert_array_equal(ray_tpu.get(ref, timeout=60), arr)


def test_large_task_arg_and_return(cluster):
    @ray_tpu.remote
    def echo(a):
        return a * 2

    arr = np.ones((500, 500))
    out = ray_tpu.get(echo.remote(arr), timeout=120)
    np.testing.assert_array_equal(out, arr * 2)


def test_error_propagation(cluster):
    @ray_tpu.remote
    def boom():
        raise RuntimeError("cluster kaboom")

    with pytest.raises(TaskError, match="cluster kaboom"):
        ray_tpu.get(boom.remote(), timeout=60)


def test_nested_tasks(cluster):
    @ray_tpu.remote
    def leaf(x):
        return x + 1

    @ray_tpu.remote
    def parent():
        return sum(ray_tpu.get([leaf.remote(i) for i in range(3)]))

    assert ray_tpu.get(parent.remote(), timeout=120) == 6


def test_actor_state_and_order(cluster):
    @ray_tpu.remote
    class Acc:
        def __init__(self):
            self.values = []

        def push(self, v):
            self.values.append(v)
            return len(self.values)

        def get_all(self):
            return self.values

    a = Acc.remote()
    for i in range(10):
        a.push.remote(i)
    assert ray_tpu.get(a.get_all.remote(), timeout=60) == list(range(10))


def test_named_actor_and_kill(cluster):
    @ray_tpu.remote
    class Svc:
        def ping(self):
            return "pong"

    Svc.options(name="svc1", num_cpus=0).remote()
    h = ray_tpu.get_actor("svc1")
    assert ray_tpu.get(h.ping.remote(), timeout=60) == "pong"
    ray_tpu.kill(h)
    time.sleep(0.5)
    with pytest.raises(ActorDiedError):
        ray_tpu.get(h.ping.remote(), timeout=60)


def test_actor_creation_failure_surfaces(cluster):
    @ray_tpu.remote
    class Bad:
        def __init__(self):
            raise ValueError("bad init")

        def m(self):
            return 1

    b = Bad.options(num_cpus=0).remote()
    with pytest.raises((ActorDiedError, TaskError)):
        ray_tpu.get(b.m.remote(), timeout=60)


def test_borrowed_ref_roundtrip(cluster):
    @ray_tpu.remote
    def producer():
        return ray_tpu.put(list(range(100)))

    inner = ray_tpu.get(producer.remote(), timeout=60)
    assert ray_tpu.get(inner, timeout=60) == list(range(100))


def test_wait_cluster(cluster):
    @ray_tpu.remote
    def slow(t):
        time.sleep(t)
        return t

    refs = [slow.remote(0.05), slow.remote(5.0)]
    ready, not_ready = ray_tpu.wait(refs, num_returns=1, timeout=30)
    assert ready == [refs[0]] and not_ready == [refs[1]]


def test_async_actor(cluster):
    @ray_tpu.remote
    class A:
        async def work(self, x):
            import asyncio

            await asyncio.sleep(0.01)
            return x * 10

    a = A.options(max_concurrency=4, num_cpus=0).remote()
    assert ray_tpu.get([a.work.remote(i) for i in range(4)], timeout=60) == [0, 10, 20, 30]


def test_cluster_resources_reflect_usage(cluster):
    res = ray_tpu.cluster_resources()
    assert res["CPU"] == 4.0


def test_runtime_env_env_vars(cluster):
    """runtime_env={'env_vars': ...}: applied for a task's duration on
    pooled workers and permanently on dedicated actor workers
    (reference ``_private/runtime_env/``)."""
    import os

    @ray_tpu.remote(runtime_env={"env_vars": {"MY_FLAG": "on"}})
    def read_flag():
        return os.environ.get("MY_FLAG")

    @ray_tpu.remote
    def read_plain():
        return os.environ.get("MY_FLAG")

    assert ray_tpu.get(read_flag.remote(), timeout=60) == "on"
    # restored afterwards: probe repeatedly so every pooled worker —
    # including the one that ran read_flag — is covered
    probes = ray_tpu.get([read_plain.remote() for _ in range(8)], timeout=120)
    assert probes == [None] * 8, probes

    @ray_tpu.remote(num_cpus=0, runtime_env={"env_vars": {"ACTOR_FLAG": "42"}})
    class EnvActor:
        def read(self):
            return os.environ.get("ACTOR_FLAG")

    a = EnvActor.remote()
    assert ray_tpu.get(a.read.remote(), timeout=60) == "42"
