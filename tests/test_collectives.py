"""Object-store collective group tests (GLOO-equivalent path).

Reference: ``ray.util.collective`` tests — here the backend is the
distributed object store + an async coordinator actor, so it needs
cluster mode (async actors)."""

import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote(num_cpus=0)
class Rank:
    def __init__(self, group_name, world_size, rank):
        from ray_tpu.parallel.collectives import CollectiveGroup

        self.group = CollectiveGroup(group_name, world_size, rank)
        self.rank = rank

    def do_allreduce(self):
        return self.group.allreduce(np.full(4, self.rank + 1.0))

    def do_allgather(self):
        return self.group.allgather(np.array([self.rank]))

    def do_broadcast(self):
        return self.group.broadcast(np.array([42.0]) if self.rank == 0 else None, root=0)

    def do_reducescatter(self):
        return self.group.reducescatter(np.arange(4, dtype=np.float64))

    def do_sendrecv(self):
        if self.rank == 0:
            self.group.send(np.array([7.0]), dst=1)
            return None
        return self.group.recv(src=0)


def test_allreduce_allgather_broadcast(cluster):
    ranks = [Rank.remote("g1", 2, r) for r in range(2)]
    out = ray_tpu.get([r.do_allreduce.remote() for r in ranks], timeout=120)
    np.testing.assert_array_equal(out[0], np.full(4, 3.0))  # 1 + 2
    np.testing.assert_array_equal(out[0], out[1])

    gathered = ray_tpu.get([r.do_allgather.remote() for r in ranks], timeout=120)
    assert [int(g[0][0]) for g in gathered] == [0, 0]
    assert [int(g[1][0]) for g in gathered] == [1, 1]

    bc = ray_tpu.get([r.do_broadcast.remote() for r in ranks], timeout=120)
    assert all(float(b[0]) == 42.0 for b in bc)


def test_reducescatter_and_p2p(cluster):
    ranks = [Rank.remote("g2", 2, r) for r in range(2)]
    rs = ray_tpu.get([r.do_reducescatter.remote() for r in ranks], timeout=120)
    np.testing.assert_array_equal(rs[0], np.array([0.0, 2.0]))  # sum of [0,1] halves
    np.testing.assert_array_equal(rs[1], np.array([4.0, 6.0]))

    out = ray_tpu.get([r.do_sendrecv.remote() for r in ranks], timeout=120)
    assert out[0] is None and float(out[1][0]) == 7.0
