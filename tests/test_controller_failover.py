"""Live controller failover under seeded chaos (E2E).

THE control-plane robustness gates, sharing ONE module-scoped cluster
(controller + 2 daemons) so the second scenario rides the same session
the first one already paid to boot:

1. **Restart-from-snapshot under reply-drop chaos**: the standalone
   controller is SIGKILLed mid-workload (tasks + actor calls + serve
   requests) while a seeded ``REPLY_DROP`` fault plan is active on every
   mutating control-plane method AND the worker push path — the
   handler-ran-but-reply-lost fault that makes blind retries duplicate
   side effects. The controller restarts from its snapshot on the SAME
   port; daemons re-register, drivers re-subscribe push channels, and
   the workload completes with ZERO client-visible errors and ZERO
   duplicate side effects (a counter actor records every operation id;
   each must land EXACTLY once).

2. **Zero-loss hot-standby takeover**: a seeded ``ControllerFaultPlan``
   (``zombie_resurrect``) silences the active's lease mid-mutation-
   burst; the hot standby replays the WAL to the tip, bumps the fencing
   epoch, announces it cluster-wide, and rebinds the old port inside
   the lease window — every *acked* mutation must be present afterwards
   (the WAL closes the snapshot-period loss window), the resurrected
   old controller must be fenced by the daemons' epoch gate
   (``raytpu_controller_fenced_writes_total``) and exit, and the burst
   completes with zero client-visible errors.

Reference analogue: GCS fault-tolerance tests (gcs restarts from Redis
mid-workload) combined with ``rpc_chaos``-style injection.
"""

import os
import pickle
import re
import signal
import threading
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.core.api import _global_worker
from ray_tpu.core.cluster_backend import _stop, spawn_controller, spawn_node
from ray_tpu.core.config import GLOBAL_CONFIG

# seeded fault-injection suite: a failure prints the copy-pasteable
# RAY_TPU_testing_* repro line (tests/conftest.py chaos helper)
pytestmark = pytest.mark.chaos

#: seeded fault plan: reply_drop on the control plane's mutating methods
#: (the dedup-required class from the issue: actor create, kv_put, node
#: register, death reports) plus the worker push path (submit/serve
#: pushes — what the side-effect counter actually guards).
CHAOS_SPEC = ",".join(
    [
        "register_actor:reply_drop:0.4",
        "actor_ready:reply_drop:0.4",
        "kv_put:reply_drop:0.4",
        "register_node:reply_drop:0.3",
        "report_actor_death:reply_drop:0.3",
        "create_pg:reply_drop:0.4",
        "push_batch:reply_drop:0.15",
        "push_task:reply_drop:0.15",
    ]
)

#: pinned chaos seed: a bare run of this file replays the exact session
#: schedule a CI failure logged (the conftest session seed, when set via
#: RAY_TPU_testing_rpc_chaos_seed, is what the repro line overrides)
CHAOS_SEED = 20260803


@pytest.fixture(scope="module")
def failover_cluster(tmp_path_factory):
    """One controller + two daemons + a connected driver, shared by both
    failover scenarios. ``st["controller"]`` always tracks the CURRENT
    active controller process (tests that kill/replace it update the
    slot); every other spawned controller lands in ``st["procs"]``."""
    old_spec = GLOBAL_CONFIG.testing_rpc_chaos
    old_seed = GLOBAL_CONFIG.testing_rpc_chaos_seed
    GLOBAL_CONFIG.testing_rpc_chaos = CHAOS_SPEC
    GLOBAL_CONFIG.testing_rpc_chaos_seed = CHAOS_SEED
    session_dir = str(tmp_path_factory.mktemp("failover") / "ctrl")
    st = {"session_dir": session_dir, "nodes": [], "procs": []}
    try:
        head = spawn_controller(session_dir)
        st["controller"] = head
        st["cport"] = head.controller_port
        st["nodes"].append(spawn_node(f"127.0.0.1:{st['cport']}", num_cpus=4))
        st["nodes"].append(spawn_node(f"127.0.0.1:{st['cport']}", num_cpus=4))
        ray_tpu.init(
            address=f"127.0.0.1:{st['cport']}:{st['nodes'][0].node_port}"
        )
        yield st
    finally:
        GLOBAL_CONFIG.testing_rpc_chaos = old_spec
        GLOBAL_CONFIG.testing_rpc_chaos_seed = old_seed
        try:
            serve.shutdown()
        except Exception:
            pass
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        for proc in st["nodes"]:
            _stop(proc)
        for proc in st["procs"] + [st.get("controller")]:
            if proc is not None and proc.poll() is None:
                _stop(proc)


def _wait_for_snapshot(snap_path: str, sentinel: bytes, timeout_s: float = 20.0):
    """Block until the controller's periodic snapshot includes ``sentinel``
    in its KV table — everything registered BEFORE the sentinel is then
    durably in the snapshot (it is a whole-table dump)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            with open(snap_path, "rb") as f:
                snap = pickle.load(f)
            if sentinel in snap.get("kv", {}):
                return snap
        except Exception:
            pass
        time.sleep(0.2)
    raise AssertionError("controller snapshot never captured the sentinel")


def test_controller_failover_under_reply_drop_chaos(failover_cluster):
    st = failover_cluster
    session_dir = st["session_dir"]
    cport = st["cport"]
    restarted = {}

    @ray_tpu.remote
    def double(x):
        return 2 * x

    @ray_tpu.remote(num_cpus=0.25)
    class Counter:
        def __init__(self):
            self.counts = {}

        def add(self, key):
            self.counts[key] = self.counts.get(key, 0) + 1
            return key

        def snapshot(self):
            return dict(self.counts)

    counter = Counter.remote()
    assert ray_tpu.get(counter.add.remote("warm"), timeout=60) == "warm"

    @serve.deployment(num_replicas=2, ray_actor_options={"num_cpus": 0.25})
    class Echo:
        def __init__(self, counter):
            self.counter = counter

        def __call__(self, x):
            # the serve request's side effect lands on the counter:
            # a duplicated execution would be visible as count == 2
            ray_tpu.get(self.counter.add.remote(f"serve-{x}"))
            return f"echo-{x}"

    handle = serve.run(Echo.bind(counter))
    assert handle.call("boot", _idempotent=False) == "echo-boot"

    backend = _global_worker().backend
    expected_keys = {"warm"}
    expected_serve = {"serve-boot"}
    kv_written = {}

    def wave(tag, n_tasks=20, n_actor=12, n_serve=6, n_kv=4):
        got = ray_tpu.get(
            [double.remote(i) for i in range(n_tasks)], timeout=120
        )
        assert got == [2 * i for i in range(n_tasks)]
        keys = [f"{tag}-a{i}" for i in range(n_actor)]
        acks = ray_tpu.get(
            [counter.add.remote(k) for k in keys], timeout=120
        )
        assert acks == keys
        expected_keys.update(keys)
        for i in range(n_serve):
            x = f"{tag}-s{i}"
            assert handle.call(x, _idempotent=False) == f"echo-{x}"
            expected_serve.add(f"serve-{x}")
        for i in range(n_kv):
            key = f"{tag}-kv{i}".encode()
            backend.kv_put(key, b"v:" + key)
            kv_written[key] = b"v:" + key

    # ---- phase 1: healthy cluster under chaos ----------------------
    wave("pre")
    # durability barrier: the counter actor, serve actors, and all
    # phase-1 state must be IN the snapshot before the kill
    backend.kv_put(b"@failover-sentinel", b"1")
    kv_written[b"@failover-sentinel"] = b"1"
    snap_path = os.path.join(session_dir, "controller_snapshot.pkl")
    snap = _wait_for_snapshot(snap_path, b"@failover-sentinel")
    assert len(snap.get("actors", {})) >= 4  # counter + serve ctl + 2 replicas

    # ---- phase 2: SIGKILL the controller mid-workload --------------
    os.kill(st["controller"].pid, signal.SIGKILL)
    st["controller"].wait(timeout=10)

    def _restart():
        time.sleep(0.75)  # a real outage window, not an instant flip
        restarted["proc"] = spawn_controller(session_dir)

    t = threading.Thread(target=_restart, daemon=True)
    t.start()
    # workload continues THROUGH the outage: calls park on reconnect
    # backoff and complete once the controller is back on its port
    wave("outage")
    t.join(timeout=30)
    assert restarted["proc"].controller_port == cport  # same address
    st["controller"] = restarted["proc"]

    # ---- phase 3: post-restart reconciliation ----------------------
    wave("post")
    # membership reconciled: both daemons re-register on their next
    # sync tick (bounded wait — the waves above don't need both nodes,
    # so the second daemon's tick may still be in its retry backoff)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        alive = [n for n in ray_tpu.nodes() if n["Alive"]]
        if len(alive) == 2:
            break
        time.sleep(0.25)
    assert len(alive) == 2
    # fresh actor creation works against the restarted controller
    c2 = Counter.remote()
    assert ray_tpu.get(c2.add.remote("fresh"), timeout=60) == "fresh"
    # kv survived the failover (snapshot + WAL) and the chaos (dedup):
    # every key present exactly with its value
    for key, val in kv_written.items():
        assert backend.kv_get(key) == val, key

    # ---- THE exactly-once assertion --------------------------------
    snap_counts = ray_tpu.get(counter.snapshot.remote(), timeout=60)
    dupes = {k: v for k, v in snap_counts.items() if v != 1}
    assert dupes == {}, f"duplicate side effects: {dupes}"
    serve_keys = {k for k in snap_counts if k.startswith("serve-")}
    actor_keys = set(snap_counts) - serve_keys
    assert actor_keys == expected_keys
    assert serve_keys == expected_serve

    # daemon observability: the reconnect is counted, not inferred
    stats = backend.io.run(backend.daemon.call("stats"))
    mport = stats.get("metrics_port", 0)
    if mport:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{mport}/metrics", timeout=5
        ).read().decode()
        assert "raytpu_controller_reconnects_total" in body


def _metric_value(text: str, name: str) -> float:
    """Sum every sample of ``name`` in a Prometheus text exposition."""
    total = 0.0
    for m in re.finditer(rf"^{name}(?:{{[^}}]*}})? ([0-9.e+-]+)$", text, re.M):
        total += float(m.group(1))
    return total


def test_standby_takeover_zero_loss(failover_cluster):
    """The zero-loss gate: seeded ``zombie_resurrect`` chaos silences
    the active controller's lease mid-mutation-burst. The hot standby
    must take over within the lease window (WAL replay to tip, epoch
    bump, same-port rebind), every ACKED mutation must be present
    afterwards, the resurrected zombie must be fenced by the daemons'
    epoch gate and exit, and the burst must see zero errors."""
    st = failover_cluster
    session_dir = st["session_dir"]
    cport = st["cport"]
    backend = _global_worker().backend

    @ray_tpu.remote(num_cpus=0.25)
    class Counter:
        def __init__(self):
            self.counts = {}

        def add(self, key):
            self.counts[key] = self.counts.get(key, 0) + 1
            return key

        def snapshot(self):
            return dict(self.counts)

    # created against the healthy incumbent — lives on a daemon worker
    # and survives every controller transition below
    counter = Counter.remote()
    assert ray_tpu.get(counter.add.remote("warm2"), timeout=60) == "warm2"

    old = (
        GLOBAL_CONFIG.testing_rpc_chaos,
        GLOBAL_CONFIG.controller_lease_interval_s,
        GLOBAL_CONFIG.controller_lease_timeout_s,
        GLOBAL_CONFIG.controller_persist_interval_s,
        GLOBAL_CONFIG.testing_controller_chaos,
        GLOBAL_CONFIG.testing_controller_chaos_seed,
    )
    zombie = standby = None
    try:
        # control-plane processes spawned below run WITHOUT reply-drop
        # chaos (this scenario injects lease faults, not RPC faults),
        # with a tight lease so the takeover window is test-sized, and
        # with snapshot compaction pushed past the scenario so the WAL
        # is unambiguously the recovery source the standby replays
        GLOBAL_CONFIG.testing_rpc_chaos = ""
        GLOBAL_CONFIG.controller_lease_interval_s = 0.25
        GLOBAL_CONFIG.controller_lease_timeout_s = 1.0
        GLOBAL_CONFIG.controller_persist_interval_s = 30.0
        # the fault plan rides ONLY in the replacement active (the
        # zombie-to-be): its first lease tick goes silent for 4s — well
        # past the lease timeout, so the standby promotes and fences
        # the epoch BEFORE the zombie resumes and probes
        GLOBAL_CONFIG.testing_controller_chaos = "zombie_resurrect:1.0:4.0:1"
        GLOBAL_CONFIG.testing_controller_chaos_seed = 20260807

        os.kill(st["controller"].pid, signal.SIGKILL)
        st["controller"].wait(timeout=10)
        zombie = spawn_controller(session_dir)
        st["procs"].append(zombie)
        st["controller"] = zombie
        assert zombie.controller_port == cport

        # head of the burst, acked by the zombie-to-be inside its
        # pre-fence window: these mutations live ONLY in its WAL (its
        # snapshot tick never comes) — exactly what the promoted
        # standby must replay to the tip
        kv_acked = {}
        for i in range(5):
            key = f"burst-kv{i}".encode()
            backend.kv_put(key, b"v:" + key)  # returns only on ack
            kv_acked[key] = b"v:" + key
            assert ray_tpu.get(
                counter.add.remote(f"burst-a{i}"), timeout=120
            ) == f"burst-a{i}"

        # the standby is spawned with a CLEAN plan — the promoted
        # incumbent must not re-trigger the fault
        GLOBAL_CONFIG.testing_controller_chaos = ""
        GLOBAL_CONFIG.testing_controller_chaos_seed = 0
        standby = spawn_controller(session_dir, standby=True)
        st["procs"].append(standby)
        assert standby.standby and standby.controller_port == cport

        # ---- the rest of the burst, spanning the whole fault -------
        # the zombie self-fences its acks once its lease goes stale;
        # the tail parks on client retries until the promoted standby
        # serves it on the same port
        K = 40
        for i in range(5, K):
            key = f"burst-kv{i}".encode()
            backend.kv_put(key, b"v:" + key)  # returns only on ack
            kv_acked[key] = b"v:" + key
            assert ray_tpu.get(
                counter.add.remote(f"burst-a{i}"), timeout=120
            ) == f"burst-a{i}"

        # the deposed zombie must have exited: its resurrected lease
        # probe hit the daemons' epoch gate and took the order
        deadline = time.monotonic() + 20
        while zombie.poll() is None and time.monotonic() < deadline:
            time.sleep(0.2)
        assert zombie.poll() is not None, "fenced zombie controller never exited"
        st["controller"] = standby

        # ---- zero loss: every acked mutation present ---------------
        for key, val in kv_acked.items():
            assert backend.kv_get(key) == val, key
        counts = ray_tpu.get(counter.snapshot.remote(), timeout=60)
        burst = {k: v for k, v in counts.items() if k.startswith("burst-a")}
        assert burst == {f"burst-a{i}": 1 for i in range(K)}  # exactly once

        # ---- the takeover is observable, not inferred --------------
        status = backend.cluster_status()
        ctrl = status["controller"]
        assert ctrl["takeover"] is True
        assert ctrl["epoch"] >= 2
        assert ctrl["recovery"]["wal_records"] > 0  # replayed to the tip

        from ray_tpu.util import state

        tel = state.cluster_telemetry()
        assert _metric_value(tel["controller"], "raytpu_controller_takeovers_total") >= 1
        assert _metric_value(tel["controller"], "raytpu_controller_epoch") >= 2
        fenced = sum(
            _metric_value(text, "raytpu_controller_fenced_writes_total")
            for text in tel["nodes"].values()
        )
        assert fenced >= 1, "zombie write was never fenced by a daemon"

        # the cluster is fully serviceable under the new incumbent
        c2 = Counter.remote()
        assert ray_tpu.get(c2.add.remote("post-takeover"), timeout=60) \
            == "post-takeover"
    finally:
        (
            GLOBAL_CONFIG.testing_rpc_chaos,
            GLOBAL_CONFIG.controller_lease_interval_s,
            GLOBAL_CONFIG.controller_lease_timeout_s,
            GLOBAL_CONFIG.controller_persist_interval_s,
            GLOBAL_CONFIG.testing_controller_chaos,
            GLOBAL_CONFIG.testing_controller_chaos_seed,
        ) = old
