"""Live controller failover under post-execution RPC chaos (E2E).

THE control-plane robustness gate: a standalone controller process is
SIGKILLed in the middle of a mixed workload (tasks + actor calls + serve
requests) while a seeded ``REPLY_DROP`` fault plan is active on every
mutating control-plane method AND on the worker push path — the
handler-ran-but-reply-lost fault that makes blind retries duplicate side
effects. The controller restarts from its snapshot on the SAME port;
daemons re-register, drivers re-subscribe push channels, and the
workload must complete with

* ZERO client-visible errors (every call retries through the outage and
  the chaos), and
* ZERO duplicate side effects (a counter actor records every operation
  id; each must land EXACTLY once — request-id dedup is what keeps the
  chaos'd retries from double-executing).

Reference analogue: GCS fault-tolerance tests (gcs restarts from Redis
mid-workload) combined with ``rpc_chaos``-style injection.
"""

import os
import pickle
import signal
import threading
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.core.api import _global_worker
from ray_tpu.core.cluster_backend import _stop, spawn_controller, spawn_node
from ray_tpu.core.config import GLOBAL_CONFIG

# seeded fault-injection suite: a failure prints the copy-pasteable
# RAY_TPU_testing_* repro line (tests/conftest.py chaos helper)
pytestmark = pytest.mark.chaos

#: seeded fault plan: reply_drop on the control plane's mutating methods
#: (the dedup-required class from the issue: actor create, kv_put, node
#: register, death reports) plus the worker push path (submit/serve
#: pushes — what the side-effect counter actually guards).
CHAOS_SPEC = ",".join(
    [
        "register_actor:reply_drop:0.4",
        "actor_ready:reply_drop:0.4",
        "kv_put:reply_drop:0.4",
        "register_node:reply_drop:0.3",
        "report_actor_death:reply_drop:0.3",
        "create_pg:reply_drop:0.4",
        "push_batch:reply_drop:0.15",
        "push_task:reply_drop:0.15",
    ]
)


def _wait_for_snapshot(snap_path: str, sentinel: bytes, timeout_s: float = 20.0):
    """Block until the controller's periodic snapshot includes ``sentinel``
    in its KV table — everything registered BEFORE the sentinel is then
    durably in the snapshot (it is a whole-table dump)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            with open(snap_path, "rb") as f:
                snap = pickle.load(f)
            if sentinel in snap.get("kv", {}):
                return snap
        except Exception:
            pass
        time.sleep(0.2)
    raise AssertionError("controller snapshot never captured the sentinel")


def test_controller_failover_under_reply_drop_chaos(tmp_path):
    old_spec = GLOBAL_CONFIG.testing_rpc_chaos
    old_seed = GLOBAL_CONFIG.testing_rpc_chaos_seed
    GLOBAL_CONFIG.testing_rpc_chaos = CHAOS_SPEC
    if not GLOBAL_CONFIG.testing_rpc_chaos_seed:
        # normally the conftest session seed is already set; pin one so a
        # bare run of this file is reproducible too
        GLOBAL_CONFIG.testing_rpc_chaos_seed = 20260803
    session_dir = str(tmp_path / "ctrl")
    head = None
    nodes = []
    restarted = {}
    try:
        head = spawn_controller(session_dir)
        cport = head.controller_port
        nodes.append(spawn_node(f"127.0.0.1:{cport}", num_cpus=4))
        nodes.append(spawn_node(f"127.0.0.1:{cport}", num_cpus=4))
        ray_tpu.init(address=f"127.0.0.1:{cport}:{nodes[0].node_port}")

        @ray_tpu.remote
        def double(x):
            return 2 * x

        @ray_tpu.remote(num_cpus=0.25)
        class Counter:
            def __init__(self):
                self.counts = {}

            def add(self, key):
                self.counts[key] = self.counts.get(key, 0) + 1
                return key

            def snapshot(self):
                return dict(self.counts)

        counter = Counter.remote()
        assert ray_tpu.get(counter.add.remote("warm"), timeout=60) == "warm"

        @serve.deployment(num_replicas=2, ray_actor_options={"num_cpus": 0.25})
        class Echo:
            def __init__(self, counter):
                self.counter = counter

            def __call__(self, x):
                # the serve request's side effect lands on the counter:
                # a duplicated execution would be visible as count == 2
                ray_tpu.get(self.counter.add.remote(f"serve-{x}"))
                return f"echo-{x}"

        handle = serve.run(Echo.bind(counter))
        assert handle.call("boot", _idempotent=False) == "echo-boot"

        backend = _global_worker().backend
        expected_keys = {"warm"}
        expected_serve = {"serve-boot"}
        kv_written = {}

        def wave(tag, n_tasks=20, n_actor=12, n_serve=6, n_kv=4):
            got = ray_tpu.get(
                [double.remote(i) for i in range(n_tasks)], timeout=120
            )
            assert got == [2 * i for i in range(n_tasks)]
            keys = [f"{tag}-a{i}" for i in range(n_actor)]
            acks = ray_tpu.get(
                [counter.add.remote(k) for k in keys], timeout=120
            )
            assert acks == keys
            expected_keys.update(keys)
            for i in range(n_serve):
                x = f"{tag}-s{i}"
                assert handle.call(x, _idempotent=False) == f"echo-{x}"
                expected_serve.add(f"serve-{x}")
            for i in range(n_kv):
                key = f"{tag}-kv{i}".encode()
                backend.kv_put(key, b"v:" + key)
                kv_written[key] = b"v:" + key

        # ---- phase 1: healthy cluster under chaos ----------------------
        wave("pre")
        # durability barrier: the counter actor, serve actors, and all
        # phase-1 state must be IN the snapshot before the kill
        backend.kv_put(b"@failover-sentinel", b"1")
        kv_written[b"@failover-sentinel"] = b"1"
        snap_path = os.path.join(session_dir, "controller_snapshot.pkl")
        snap = _wait_for_snapshot(snap_path, b"@failover-sentinel")
        assert len(snap.get("actors", {})) >= 4  # counter + serve ctl + 2 replicas

        # ---- phase 2: SIGKILL the controller mid-workload --------------
        os.kill(head.pid, signal.SIGKILL)
        head.wait(timeout=10)

        def _restart():
            time.sleep(0.75)  # a real outage window, not an instant flip
            restarted["proc"] = spawn_controller(session_dir)

        t = threading.Thread(target=_restart, daemon=True)
        t.start()
        # workload continues THROUGH the outage: calls park on reconnect
        # backoff and complete once the controller is back on its port
        wave("outage")
        t.join(timeout=30)
        assert restarted["proc"].controller_port == cport  # same address

        # ---- phase 3: post-restart reconciliation ----------------------
        wave("post")
        # membership reconciled: both daemons re-registered
        alive = [n for n in ray_tpu.nodes() if n["Alive"]]
        assert len(alive) == 2
        # fresh actor creation works against the restarted controller
        c2 = Counter.remote()
        assert ray_tpu.get(c2.add.remote("fresh"), timeout=60) == "fresh"
        # kv survived the failover (snapshot) and the chaos (dedup):
        # every key present exactly with its value
        for key, val in kv_written.items():
            assert backend.kv_get(key) == val, key

        # ---- THE exactly-once assertion --------------------------------
        snap_counts = ray_tpu.get(counter.snapshot.remote(), timeout=60)
        dupes = {k: v for k, v in snap_counts.items() if v != 1}
        assert dupes == {}, f"duplicate side effects: {dupes}"
        serve_keys = {k for k in snap_counts if k.startswith("serve-")}
        actor_keys = set(snap_counts) - serve_keys
        assert actor_keys == expected_keys
        assert serve_keys == expected_serve

        # daemon observability: the reconnect is counted, not inferred
        stats = backend.io.run(backend.daemon.call("stats"))
        mport = stats.get("metrics_port", 0)
        if mport:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{mport}/metrics", timeout=5
            ).read().decode()
            assert "raytpu_controller_reconnects_total" in body
    finally:
        GLOBAL_CONFIG.testing_rpc_chaos = old_spec
        GLOBAL_CONFIG.testing_rpc_chaos_seed = old_seed
        try:
            serve.shutdown()
        except Exception:
            pass
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        for proc in nodes:
            _stop(proc)
        if restarted.get("proc") is not None:
            _stop(restarted["proc"])
        if head is not None and head.poll() is None:
            _stop(head)
