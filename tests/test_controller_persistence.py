"""Controller snapshot/restart recovery (reference: GCS rebuilds from
Redis tables on restart, ``gcs_init_data.cc``; raylets reconnect and
running actors are adopted)."""

import asyncio
import os

import pytest

from ray_tpu.core.controller import Controller
from ray_tpu.core.ids import ActorID, JobID, TaskID
from ray_tpu.core.task_spec import TaskKind, TaskSpec


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _actor_spec(name="worker_actor"):
    job = JobID.from_index(1)
    actor_id = ActorID.of(job)
    return TaskSpec(
        kind=TaskKind.ACTOR_CREATION,
        task_id=TaskID.for_task(ActorID.nil_for_job(job)),
        job_id=job.binary(),
        name=name,
        function_id=b"f" * 8,
        num_returns=1,
        return_ids=[],
        resources={"CPU": 1.0},
        owner=None,
        actor_id=actor_id,
        max_restarts=1,
    )


def test_snapshot_roundtrip(tmp_path):
    path = str(tmp_path / "snap.pkl")

    async def phase1():
        c = Controller(port=0, persist_path=path)
        await c.start()
        # KV + named pg tables + an ALIVE actor
        await c.c_kv_put({"key": b"fn:abc", "value": b"pickled-fn"}, None)
        spec = _actor_spec()
        await c.c_register_actor({"spec": spec}, None)
        c.named_actors[("", "myactor")] = spec.actor_id
        c.actors[spec.actor_id].state = "ALIVE"
        await c.c_create_pg(
            {"pg_id": b"p" * 12, "bundles": [{"CPU": 1.0}], "strategy": "PACK", "name": "pg1"},
            None,
        )
        # force a snapshot write (the loop runs at 1s)
        await asyncio.sleep(1.5)
        await c.stop()
        return spec

    spec = _run(phase1())
    assert os.path.exists(path)

    async def phase2():
        c2 = Controller(port=0, persist_path=path)
        await c2.start()
        try:
            assert c2.kv[b"fn:abc"] == b"pickled-fn"
            assert c2.named_actors[("", "myactor")] == spec.actor_id
            info = c2.actors[spec.actor_id]
            assert info.state == "RESTARTING" and info.restored
            assert b"p" * 12 in c2.pgs
            # real flow: daemon re-registers (unknown-node reply) and THEN
            # its sync adopts the running actor back to ALIVE
            reply = await c2.c_sync_resources(
                {"node_id": b"n" * 16, "available": {"CPU": 4.0}}, None
            )
            assert reply.get("unknown_node")
            await c2.c_register_node(
                {"node_id": b"n" * 16, "host": "127.0.0.1", "port": 1,
                 "resources": {"CPU": 4.0}},
                None,
            )
            await c2.c_sync_resources(
                {
                    "node_id": b"n" * 16,
                    "available": {"CPU": 4.0},
                    "actors": [
                        {
                            "actor_id": spec.actor_id,
                            "host": "127.0.0.1",
                            "port": 12345,
                            "pid": 999,
                        }
                    ],
                },
                None,
            )
            info = c2.actors[spec.actor_id]
            assert info.state == "ALIVE"
            assert info.address.port == 12345
            assert not info.restored
        finally:
            await c2.stop()

    _run(phase2())


def test_no_snapshot_is_clean_start(tmp_path):
    async def go():
        c = Controller(port=0, persist_path=str(tmp_path / "missing.pkl"))
        await c.start()
        assert not c.kv and not c.actors and not c.pgs
        await c.stop()

    _run(go())


def test_restart_with_live_daemon_readopts_pg(tmp_path):
    """Full restart: controller dies and comes back on its old port; the
    surviving daemon re-registers (unknown-node sync reply) carrying its
    committed bundles, and the restored PG is re-adopted — no
    double-reservation, no reschedule."""
    from ray_tpu.core.config import GLOBAL_CONFIG
    from ray_tpu.core.node_daemon import NodeDaemon

    path = str(tmp_path / "snap.pkl")
    old_grace = GLOBAL_CONFIG.controller_restore_grace_s
    GLOBAL_CONFIG.controller_restore_grace_s = 2.0

    async def go():
        c1 = Controller(port=0, persist_path=path)
        cport = await c1.start()
        daemon = NodeDaemon(
            "127.0.0.1", cport, resources={"CPU": 4.0},
            session_dir=str(tmp_path / "sess"),
        )
        await daemon.start()
        try:
            # create + commit a PG
            await c1.c_create_pg(
                {"pg_id": b"q" * 12, "bundles": [{"CPU": 2.0}],
                 "strategy": "PACK", "name": ""},
                None,
            )
            for _ in range(100):
                if c1.pgs[b"q" * 12].state == "CREATED":
                    break
                await asyncio.sleep(0.1)
            assert c1.pgs[b"q" * 12].state == "CREATED"
            assert (b"q" * 12, 0) in daemon._bundle_pools
            await asyncio.sleep(1.5)  # let a snapshot land
            await c1.stop()

            # restart on the same port (snapshot rebind)
            c2 = Controller(port=0, persist_path=path)
            cport2 = await c2.start()
            assert cport2 == cport  # rebound the old port
            try:
                assert c2.pgs[b"q" * 12].state == "RESTORING"
                # daemon sync -> unknown_node -> re-register with bundles
                deadline = asyncio.get_event_loop().time() + 10
                while asyncio.get_event_loop().time() < deadline:
                    if c2.pgs[b"q" * 12].reservations:
                        break
                    await asyncio.sleep(0.2)
                assert c2.pgs[b"q" * 12].reservations, "bundle not re-adopted"
                # after the grace window the PG flips CREATED (re-adopted,
                # not rescheduled: the daemon still holds ONE pool)
                await asyncio.sleep(2.5)
                assert c2.pgs[b"q" * 12].state == "CREATED"
                assert len(daemon._bundle_pools) == 1
            finally:
                await c2.stop()
        finally:
            await daemon.stop()

    try:
        _run(go())
    finally:
        GLOBAL_CONFIG.controller_restore_grace_s = old_grace
