"""Controller WAL, fencing-epoch, and loss-window units (cluster-free).

The snapshot loop alone leaves a loss window of up to one persist period:
a SIGKILL between ticks silently drops every mutation acked since the
last snapshot. These tests pin the WAL contract that closes it
(``core/wal.py`` + ``Controller._wal_append``) at three levels:

1. the log format itself — framed-record roundtrip, torn-tail recovery,
   compaction truncate, and the standby's offset tailer;
2. the fencing-epoch gate — a daemon rejects (and counts) any write
   carrying a lower controller epoch, both as a policy unit and over a
   real RPC server with the epoch riding the wire meta;
3. the loss window, live — a spawned controller is SIGKILLed by the
   seeded ``kill_mid_mutation`` chaos mode in the middle of a mutation
   burst, *between the WAL append and the RPC reply*, and the restarted
   incarnation must serve every acked mutation (and, via the replay-
   seeded dedup cache, answer the in-flight retry without re-executing).
"""

import os
import signal
import threading
import time

import pytest

from ray_tpu.core import wal as walmod

pytestmark = pytest.mark.chaos


# ---- layer 1: the log format -------------------------------------------


def test_wal_roundtrip_torn_tail_and_truncate(tmp_path):
    path = str(tmp_path / "t.wal")
    w = walmod.WalWriter(path, fsync_every=1)
    records = [{"op": "kv_put", "d": {"key": b"k%d" % i, "value": b"v" * i}} for i in range(10)]
    for rec in records:
        assert w.append(rec) > walmod._HDR.size
    assert w.appended == 10
    assert list(walmod.replay(path)) == records

    # torn tail: a crash mid-append leaves a partial frame — replay must
    # yield every intact record and drop ONLY the torn one
    blob = open(path, "rb").read()
    with open(path, "ab") as f:
        f.write(walmod.pack_record({"op": "torn"})[:-3])
    assert list(walmod.replay(path)) == records

    # corrupt body (bit flip inside the LAST intact record) stops replay
    # at the corrupted frame
    flipped = bytearray(blob)
    flipped[-2] ^= 0xFF
    open(path, "wb").write(bytes(flipped))
    got = list(walmod.replay(path))
    assert got == records[:9]

    # truncate = compaction point: the log restarts empty and appends
    # keep working on the fresh file
    open(path, "wb").write(blob)
    w2 = walmod.WalWriter(path, fsync_every=0)
    w2.truncate()
    assert list(walmod.replay(path)) == []
    w2.append({"op": "after"})
    assert [r["op"] for r in walmod.replay(path)] == ["after"]
    w.close()
    w2.close()


def test_wal_scan_tip_tails_and_survives_truncation(tmp_path):
    """The standby's tailer counts intact records incrementally and
    restarts from the head when compaction shrinks the file under its
    offset."""
    path = str(tmp_path / "t.wal")
    assert walmod.scan_tip(path, 0) == (0, 0)  # absent file
    w = walmod.WalWriter(path, fsync_every=0)
    for i in range(5):
        w.append({"i": i})
    off, n = walmod.scan_tip(path, 0)
    assert n == 5 and off == os.path.getsize(path)
    w.append({"i": 5})
    off2, n2 = walmod.scan_tip(path, off)
    assert n2 == 1 and off2 > off
    # compaction: offset now beyond EOF -> tailer resets to the head
    w.truncate()
    w.append({"i": 6})
    off3, n3 = walmod.scan_tip(path, off2)
    assert n3 == 1 and off3 == os.path.getsize(path)
    w.close()


def test_lease_file_roundtrip(tmp_path):
    path = str(tmp_path / "c.lease")
    assert walmod.read_lease(path) is None
    walmod.write_lease(path, epoch=3, port=1234, pid=42, ts=99.5)
    assert walmod.read_lease(path) == {
        "epoch": 3, "port": 1234, "pid": 42, "ts": 99.5,
    }
    # clean release stamps ts=0 (the standby's instant-promote signal)
    walmod.write_lease(path, epoch=3, port=1234, pid=42, ts=0.0)
    assert walmod.read_lease(path)["ts"] == 0.0


def test_controller_fault_plan_schedule_is_seeded():
    """Determinism contract: the injection schedule is a pure function
    of (seed, consulted phases); the kill modes honour their
    skip-window param and the per-process cap."""
    from ray_tpu.util.chaos import ControllerFaultPlan

    def schedule(seed):
        plan = ControllerFaultPlan("kill_mid_mutation:0.5:3:2", seed)
        return [plan.consult("mutation") for _ in range(40)]

    assert schedule(7) == schedule(7)
    fired = [i for i, hit in enumerate(schedule(7)) if hit]
    assert len(fired) == 2          # cap
    assert all(i >= 3 for i in fired)  # skip window

    # lease modes carry their silence param through
    plan = ControllerFaultPlan("zombie_resurrect:1.0:2.5:1", 1)
    assert plan.consult("mutation") is None  # wrong phase, draw still burned
    assert plan.consult("lease") == ("zombie_resurrect", 2.5)
    assert plan.consult("lease") is None  # capped


# ---- layer 2: fencing epochs -------------------------------------------


def _fenced_count() -> float:
    from ray_tpu.observability.rpc_metrics import CONTROLLER_FENCED_WRITES

    return CONTROLLER_FENCED_WRITES._values.get((), 0.0)


def test_epoch_gate_rejects_lower_and_counts():
    """Policy unit: the daemon's gate is monotonic — it learns the
    highest epoch seen and bounces anything lower with a structured
    ``stale_controller`` error, incrementing the fenced-writes counter."""
    from ray_tpu.core.node_daemon import NodeDaemon
    from ray_tpu.core.rpc import StaleControllerError

    d = NodeDaemon.__new__(NodeDaemon)  # policy-only instance
    d._controller_epoch_seen = 0
    assert d._controller_epoch_gate("kv_put", 3) is None
    assert d._controller_epoch_seen == 3
    assert d._controller_epoch_gate("kv_put", 7) is None  # takeover raises floor
    before = _fenced_count()
    err = d._controller_epoch_gate("register_actor", 3)  # the zombie's write
    assert isinstance(err, StaleControllerError)
    assert err.seen_epoch == 7
    assert "stale_controller" in str(err)
    assert _fenced_count() == before + 1
    # equal epoch is NOT stale (the incumbent's own writes)
    assert d._controller_epoch_gate("kv_put", 7) is None


def test_epoch_rides_rpc_meta_and_fences_on_the_wire():
    """Wire-level: a client with ``fencing_epoch`` set stamps the epoch
    into RPC meta slot 3; the server's ``epoch_gate`` hook bounces a
    lower-epoch call BEFORE the handler (or its dedup record) runs,
    while epoch-less clients are never gated."""
    from ray_tpu.core.rpc import (
        IoThread,
        RpcClient,
        RpcServer,
        StaleControllerError,
    )

    io = IoThread("fence-io")
    ran = []
    seen = {"floor": 5}

    def gate(method, epoch):
        if epoch < seen["floor"]:
            return StaleControllerError(
                f"stale_controller: {method} epoch {epoch}",
                seen_epoch=seen["floor"],
            )
        seen["floor"] = max(seen["floor"], epoch)
        return None

    async def setup():
        server = RpcServer()
        server.epoch_gate = gate

        async def mutate(payload, conn):
            ran.append(payload)
            return "ok"

        server.register("mutate", mutate)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    try:
        zombie = RpcClient("127.0.0.1", port, name="zombie")
        zombie.fencing_epoch = 3
        with pytest.raises(StaleControllerError) as exc:
            io.run(zombie.call("mutate", {"from": "zombie"}, retries=0))
        assert exc.value.seen_epoch == 5
        assert ran == []  # fenced before the handler

        incumbent = RpcClient("127.0.0.1", port, name="incumbent")
        incumbent.fencing_epoch = 9
        assert io.run(incumbent.call("mutate", {"from": "new"}, retries=0)) == "ok"
        assert seen["floor"] == 9  # the hello raised the floor...
        with pytest.raises(StaleControllerError):
            io.run(zombie.call("mutate", {}, retries=0))  # ...zombie stays out

        plain = RpcClient("127.0.0.1", port, name="plain")
        assert io.run(plain.call("mutate", {"from": "plain"}, retries=0)) == "ok"
        assert [p["from"] for p in ran] == ["new", "plain"]
        io.run(zombie.close())
        io.run(incumbent.close())
        io.run(plain.close())
        io.run(server.stop())
    finally:
        io.stop()


# ---- layer 3: the loss window, live ------------------------------------


def test_kill_mid_mutation_loses_nothing(tmp_path):
    """THE loss-window gate, cluster-free: seeded ``kill_mid_mutation``
    chaos SIGKILLs a standalone controller after the WAL append but
    BEFORE the RPC reply of mutation K+1 — the worst crash point: K
    acked mutations live only in the WAL (no snapshot tick has run), and
    one mutation is durable but unacked. The restarted incarnation must
    rebind the same port, serve all K acked keys, bump its epoch, and
    answer the in-flight retry from the replay-seeded dedup cache."""
    from ray_tpu.core.cluster_backend import _stop, spawn_controller
    from ray_tpu.core.config import GLOBAL_CONFIG
    from ray_tpu.core.rpc import IoThread, RpcClient

    K = 12
    session_dir = str(tmp_path / "sd")
    old = (GLOBAL_CONFIG.testing_controller_chaos,
           GLOBAL_CONFIG.testing_controller_chaos_seed)
    # skip window = K mutation consults: puts 1..K ack normally, the
    # K+1th append pulls the trigger (prob 1.0, cap 1)
    GLOBAL_CONFIG.testing_controller_chaos = f"kill_mid_mutation:1.0:{K}:1"
    GLOBAL_CONFIG.testing_controller_chaos_seed = 20260807
    io = IoThread("wal-io")
    head = restarted = cli = None
    try:
        head = spawn_controller(session_dir)
    finally:
        GLOBAL_CONFIG.testing_controller_chaos = old[0]
        GLOBAL_CONFIG.testing_controller_chaos_seed = old[1]
    try:
        port = head.controller_port
        cli = RpcClient("127.0.0.1", port, name="controller",
                        role="controller", default_retries=40)
        for i in range(K):
            assert io.run(cli.call(
                "kv_put", {"key": b"k%d" % i, "value": b"v%d" % i},
                timeout=30,
            )) is True

        box = {}

        def _restart():
            head.wait(timeout=30)  # the chaos kill
            box["proc"] = spawn_controller(session_dir)  # clean config

        t = threading.Thread(target=_restart, daemon=True)
        t.start()
        # mutation K+1: the controller appends its WAL record, then the
        # seeded plan SIGKILLs the process before the reply — the client
        # retries through the outage and must get the CACHED reply from
        # the restarted incarnation (dedup re-seeded by replay)
        assert io.run(cli.call(
            "kv_put", {"key": b"boom", "value": b"unacked"},
            timeout=60, retries=60,
        )) is True
        t.join(timeout=30)
        restarted = box.get("proc")
        assert restarted is not None and restarted.controller_port == port

        for i in range(K):
            assert io.run(cli.call("kv_get", {"key": b"k%d" % i}, timeout=10)) \
                == b"v%d" % i
        assert io.run(cli.call("kv_get", {"key": b"boom"}, timeout=10)) == b"unacked"

        st = io.run(cli.call("cluster_status", {}, timeout=10))
        ctrl = st["controller"]
        assert ctrl["epoch"] >= 2  # restart bumped the incarnation epoch
        # every pre-kill mutation came back through WAL replay (no
        # snapshot tick ever committed)
        assert ctrl["recovery"]["wal_records"] >= K + 1
        assert ctrl["recovery"]["kv"] >= K + 1
    finally:
        if cli is not None:
            io.run(cli.close())
        io.stop()
        for proc in (head, restarted):
            if proc is not None and proc.poll() is None:
                _stop(proc)
