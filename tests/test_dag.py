"""Compiled graphs (aDAG): classic execute, compiled pipelines, channels.

Reference behaviors: ``python/ray/dag/tests/experimental/test_accelerated_dag.py``
(echo loops, error propagation, teardown) and
``test_accelerated_dag.py:1962`` (``test_simulate_pipeline_parallelism``).
"""

from __future__ import annotations

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
class Echo:
    def echo(self, x):
        return x

    def double(self, x):
        return x * 2

    def boom(self, x):
        raise ValueError("boom")

    def add(self, a, b):
        return a + b


@ray_tpu.remote
class MatmulStage:
    def __init__(self, seed):
        rng = np.random.default_rng(seed)
        self.w = rng.standard_normal((16, 16)).astype(np.float32)

    def forward(self, x):
        return x @ self.w


@ray_tpu.remote
def plus_one(x):
    return x + 1


class TestClassicExecute:
    def test_function_chain(self, cluster):
        with InputNode() as inp:
            dag = plus_one.bind(plus_one.bind(inp))
        assert ray_tpu.get(dag.execute(1), timeout=60) == 3

    def test_actor_chain(self, cluster):
        a = Echo.remote()
        with InputNode() as inp:
            dag = a.double.bind(a.double.bind(inp))
        assert ray_tpu.get(dag.execute(3), timeout=60) == 12


class TestCompiled:
    def test_two_actor_pipeline(self, cluster):
        a, b = Echo.remote(), Echo.remote()
        with InputNode() as inp:
            dag = b.double.bind(a.double.bind(inp))
        compiled = dag.experimental_compile()
        try:
            for i in range(20):
                assert compiled.execute(i).get(timeout=30) == i * 4
        finally:
            compiled.teardown()

    def test_compiled_faster_than_remote(self, cluster):
        """The whole point: steady-state executions beat .remote() round
        trips by a wide margin (VERDICT target: 10x; assert 3x so the
        noisy 1-vCPU box can't flake the suite)."""
        a, b = Echo.remote(), Echo.remote()
        # warm the normal path
        ray_tpu.get(b.echo.remote(ray_tpu.get(a.echo.remote(0), timeout=30)), timeout=30)
        n = 50
        start = time.perf_counter()
        for i in range(n):
            ray_tpu.get(b.echo.remote(ray_tpu.get(a.echo.remote(i), timeout=30)), timeout=30)
        remote_dt = (time.perf_counter() - start) / n

        with InputNode() as inp:
            dag = b.echo.bind(a.echo.bind(inp))
        compiled = dag.experimental_compile()
        try:
            compiled.execute(0).get(timeout=30)  # warm the loops
            start = time.perf_counter()
            for i in range(n):
                assert compiled.execute(i).get(timeout=30) == i
            compiled_dt = (time.perf_counter() - start) / n
        finally:
            compiled.teardown()
        speedup = remote_dt / compiled_dt
        assert speedup >= 3.0, (
            f"compiled {compiled_dt*1e6:.0f}us vs remote {remote_dt*1e6:.0f}us "
            f"({speedup:.1f}x)"
        )

    def test_multi_arg_input_and_multi_output(self, cluster):
        a, b = Echo.remote(), Echo.remote()
        with InputNode() as inp:
            s = a.add.bind(inp[0], inp[1])
            dag = MultiOutputNode([s, b.double.bind(inp[0])])
        compiled = dag.experimental_compile()
        try:
            out = compiled.execute(2, 3).get(timeout=30)
            assert out == [5, 4]
        finally:
            compiled.teardown()

    def test_error_propagation(self, cluster):
        a, b = Echo.remote(), Echo.remote()
        with InputNode() as inp:
            dag = b.double.bind(a.boom.bind(inp))
        compiled = dag.experimental_compile()
        try:
            with pytest.raises(ValueError, match="boom"):
                compiled.execute(1).get(timeout=30)
            # the pipeline must still be alive for the next execution
            with pytest.raises(ValueError, match="boom"):
                compiled.execute(2).get(timeout=30)
        finally:
            compiled.teardown()

    def test_actor_usable_after_teardown(self, cluster):
        a = Echo.remote()
        with InputNode() as inp:
            dag = a.double.bind(inp)
        compiled = dag.experimental_compile()
        assert compiled.execute(5).get(timeout=30) == 10
        compiled.teardown()
        # the loop released the actor's lane: normal calls work again
        assert ray_tpu.get(a.double.remote(7), timeout=30) == 14

    def test_pipelined_executions(self, cluster):
        """Multiple executions in flight before any get (ring buffering)."""
        a = Echo.remote()
        with InputNode() as inp:
            dag = a.double.bind(inp)
        compiled = dag.experimental_compile()
        try:
            refs = [compiled.execute(i) for i in range(6)]
            assert [r.get(timeout=30) for r in refs] == [0, 2, 4, 6, 8, 10]
        finally:
            compiled.teardown()

    def test_pp_style_two_stage_inference(self, cluster):
        """PP-style serving: two stages, each owning its weights, chained
        through channels; numerics must match a local pipeline
        (reference test_simulate_pipeline_parallelism)."""
        s1, s2 = MatmulStage.remote(1), MatmulStage.remote(2)
        with InputNode() as inp:
            dag = s2.forward.bind(s1.forward.bind(inp))
        compiled = dag.experimental_compile()
        try:
            rng = np.random.default_rng(0)
            w1 = np.random.default_rng(1).standard_normal((16, 16)).astype(np.float32)
            w2 = np.random.default_rng(2).standard_normal((16, 16)).astype(np.float32)
            for _ in range(3):
                x = rng.standard_normal((4, 16)).astype(np.float32)
                out = compiled.execute(x).get(timeout=30)
                np.testing.assert_allclose(out, x @ w1 @ w2, rtol=1e-4, atol=1e-4)
        finally:
            compiled.teardown()

    def test_value_too_large_for_slot(self, cluster):
        a = Echo.remote()
        with InputNode() as inp:
            dag = a.echo.bind(inp)
        compiled = dag.experimental_compile(_buffer_size_bytes=1024)
        try:
            with pytest.raises(ValueError, match="slot size"):
                compiled.execute(np.zeros(1 << 20, dtype=np.uint8))
        finally:
            compiled.teardown()


def test_dag_allreduce_collective_node(cluster):
    """DAG allreduce (reference dag/collective_node.py:127): each
    participating actor contributes its shard and receives the reduced
    value locally, every execution."""
    import numpy as np

    from ray_tpu.dag import InputNode, MultiOutputNode
    from ray_tpu.dag.collective import allreduce

    @ray_tpu.remote(num_cpus=0.5)
    class Shard:
        def __init__(self, scale):
            self.scale = scale

        def compute(self, x):
            return np.asarray(x, np.float64) * self.scale

        def label(self, reduced):
            return float(np.sum(reduced))

    a1, a2 = Shard.remote(1.0), Shard.remote(10.0)
    with InputNode() as inp:
        s1 = a1.compute.bind(inp)
        s2 = a2.compute.bind(inp)
        r1, r2 = allreduce.bind([s1, s2], op="sum")
        dag = MultiOutputNode([a1.label.bind(r1), a2.label.bind(r2)])
    compiled = dag.experimental_compile()
    try:
        for k in (1, 2, 3):
            x = np.full(4, float(k))
            out = ray_tpu.get(compiled.execute(x), timeout=60)
            # each actor sees sum of both shards: k*(1+10) per element * 4
            assert out == [44.0 * k, 44.0 * k], out
    finally:
        compiled.teardown()


def test_dag_device_transport_contract(cluster):
    """with_tensor_transport('device'): same-actor chains compile (the
    value passes by reference, zero copies); a cross-process consumer is
    rejected at compile time (TPU has no device IPC)."""
    import numpy as np

    from ray_tpu.dag import InputNode

    @ray_tpu.remote(num_cpus=0.5)
    class Stage:
        def load(self, x):
            import jax.numpy as jnp

            return jnp.asarray(np.asarray(x, np.float32)) * 2

        def reduce(self, arr):
            # consumes the device array produced by load IN-PROCESS:
            # with a transfer guard, any host round-trip would raise
            import jax

            with jax.transfer_guard_device_to_host("disallow"):
                doubled = arr + 1
            return float(doubled.sum())

    s = Stage.remote()
    with InputNode() as inp:
        loaded = s.load.bind(inp).with_tensor_transport("device")
        dag = s.reduce.bind(loaded)
    compiled = dag.experimental_compile()
    try:
        out = ray_tpu.get(compiled.execute(np.ones(8)), timeout=60)
        assert out == (2.0 + 1.0) * 8
    finally:
        compiled.teardown()

    # cross-process consumer of a device-annotated node must be rejected
    s2 = Stage.remote()
    with InputNode() as inp:
        loaded = s.load.bind(inp).with_tensor_transport("device")
        bad = s2.reduce.bind(loaded)
    with pytest.raises(ValueError, match="device"):
        bad.experimental_compile()
