"""ray_tpu.data tests: blocks, transforms, streaming, splits, file IO,
and the JaxTrainer ingest path (reference test model:
``python/ray/data/tests/`` + ``train/tests`` data-ingest cases)."""

import os

import numpy as np
import pytest

import ray_tpu
import ray_tpu.data as rd


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_range_count_take(cluster):
    ds = rd.range(1000)
    assert ds.count() == 1000
    assert ds.take(5) == [0, 1, 2, 3, 4]
    assert ds.schema() == {"value": "int64"}


def test_map_batches_fused(cluster):
    ds = rd.range(512).map_batches(lambda b: {"value": b["value"] * 2})
    ds = ds.map_batches(lambda b: {"value": b["value"] + 1})
    got = sorted(ds.take_all())
    assert got == [2 * i + 1 for i in range(512)]


def test_map_filter_flat_map(cluster):
    ds = rd.range(100).map(lambda x: x + 1).filter(lambda x: x % 2 == 0)
    assert sorted(ds.take_all()) == [i for i in range(1, 101) if i % 2 == 0]
    fm = rd.from_items([1, 2]).flat_map(lambda x: [x] * 3)
    assert sorted(fm.take_all()) == [1, 1, 1, 2, 2, 2]


def test_iter_batches_sizes(cluster):
    ds = rd.range(1000)
    sizes = [len(b["value"]) for b in ds.iter_batches(batch_size=300)]
    assert sizes == [300, 300, 300, 100]
    sizes = [len(b["value"]) for b in ds.iter_batches(batch_size=300, drop_last=True)]
    assert sizes == [300, 300, 300]


def test_from_items_structured(cluster):
    ds = rd.from_items([{"x": i, "y": 2 * i} for i in range(50)])
    batch = next(ds.iter_batches(batch_size=50))
    assert batch["x"].shape == (50,)
    np.testing.assert_array_equal(batch["y"], 2 * batch["x"])


def test_random_shuffle_and_repartition(cluster):
    ds = rd.range(256).random_shuffle(seed=1)
    vals = ds.take_all()
    assert sorted(vals) == list(range(256))
    assert vals != list(range(256))  # actually shuffled
    rp = ds.repartition(4)
    assert rp.count() == 256


def test_limit_and_split(cluster):
    ds = rd.range(100)
    assert sorted(ds.limit(30).take_all()) == list(range(30))
    parts = ds.split(3)
    all_vals = sorted(v for p in parts for v in p.take_all())
    assert all_vals == list(range(100))
    assert abs(parts[0].count() - parts[1].count()) <= 67


def test_streaming_split_disjoint_and_complete(cluster):
    ds = rd.range(500)
    splits = ds.streaming_split(3)
    seen = []
    for s in splits:
        for b in s.iter_batches(batch_size=None):
            seen.extend(b["value"].tolist())
    assert sorted(seen) == list(range(500))
    assert len(seen) == len(set(seen))  # disjoint


def test_parquet_roundtrip(cluster, tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    for i in range(3):
        table = pa.table({"a": list(range(i * 10, i * 10 + 10)), "b": [float(x) for x in range(10)]})
        pq.write_table(table, os.path.join(tmp_path, f"part-{i}.parquet"))
    ds = rd.read_parquet(str(tmp_path))
    assert ds.count() == 30
    assert sorted(r["a"] for r in ds.take_all()) == list(range(30))


def test_csv_roundtrip(cluster, tmp_path):
    p = os.path.join(tmp_path, "t.csv")
    with open(p, "w") as f:
        f.write("x,y\n")
        for i in range(20):
            f.write(f"{i},{i*i}\n")
    ds = rd.read_csv(p)
    rows = ds.take_all()
    assert len(rows) == 20
    assert rows[3]["y"] == 9


def test_trainer_ingests_dataset(cluster):
    """JaxTrainer + streaming_split: each rank consumes its disjoint
    shard via train.get_dataset_shard — the trainer duck-typing at
    trainer.py is now backed by a real Dataset."""
    from ray_tpu import train
    from ray_tpu.train import JaxBackendConfig, JaxTrainer, RunConfig, ScalingConfig

    def train_fn(config):
        ctx = train.get_context()
        shard = train.get_dataset_shard("train")
        assert shard is not None
        total = 0
        count = 0
        for batch in shard.iter_batches(batch_size=64):
            total += int(batch["value"].sum())
            count += len(batch["value"])
        train.report({"total": total, "count": count, "rank": ctx.get_world_rank()})

    ds = rd.range(1000, block_size=100)  # 10 blocks -> 5 per rank
    trainer = JaxTrainer(
        train_fn,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=2),
        backend_config=JaxBackendConfig(distributed=False),
        run_config=RunConfig(name="data-ingest"),
        datasets={"train": ds},
    )
    result = trainer.fit()
    # rank 0's metrics win; its shard is a strict half of the rows.
    assert result.metrics["count"] == 500
    assert result.metrics_history


def test_transform_after_materialized(cluster):
    """Chaining transforms after shuffle/limit (materialized datasets)
    sees the data (regression: _chain used to drop materialized refs)."""
    ds = rd.range(256).random_shuffle(seed=1).map(lambda x: x + 1)
    assert sorted(ds.take_all()) == list(range(1, 257))
    ds2 = rd.range(100).limit(10).map_batches(lambda b: {"value": b["value"] * 10})
    assert sorted(ds2.take_all()) == [i * 10 for i in range(10)]


def test_streaming_split_reiterable(cluster):
    """Shards are re-iterable — epoch 2 re-executes the plan (reference
    ray.train shard semantics)."""
    splits = rd.range(300, block_size=50).streaming_split(2)
    for epoch in range(2):
        seen = []
        for s in splits:
            for b in s.iter_batches(batch_size=None):
                seen.extend(b["value"].tolist())
        assert sorted(seen) == list(range(300)), f"epoch {epoch}"


def test_streaming_split_equal(cluster):
    splits = rd.range(1000, block_size=300).streaming_split(4, equal=True)
    counts = [sum(len(b["value"]) for b in s.iter_batches(batch_size=None)) for s in splits]
    assert counts == [250, 250, 250, 250]


def test_early_abandonment_stops_prefetch(cluster):
    """take()/breaking out of iter_batches doesn't leak a blocked
    producer thread."""
    import threading

    before = threading.active_count()
    for _ in range(5):
        ds = rd.range(10000, block_size=100)
        assert ds.take(3) == [0, 1, 2]
    import time as _t

    _t.sleep(1.0)  # let producer threads observe the stop flag
    after = threading.active_count()
    assert after - before < 5, (before, after)


# ---------------------------------------------------------------------------
# groupby / aggregate / sort / zip / union (reference grouped_data.py)


def test_groupby_wordcount(cluster):
    words = ["a", "b", "a", "c", "b", "a", "c", "a", "b", "c", "d"]
    ds = rd.from_items([{"word": w} for w in words])
    out = ds.groupby("word").count().take_all()
    counts = {r["word"]: int(r["count()"]) for r in out}
    assert counts == {"a": 4, "b": 3, "c": 3, "d": 1}


def test_groupby_aggregates(cluster):
    rows = [{"k": i % 3, "v": float(i)} for i in range(30)]
    ds = rd.from_items(rows)
    sums = {r["k"]: r["sum(v)"] for r in ds.groupby("k").sum("v").take_all()}
    assert sums == {
        0: sum(float(i) for i in range(0, 30, 3)),
        1: sum(float(i) for i in range(1, 30, 3)),
        2: sum(float(i) for i in range(2, 30, 3)),
    }
    means = {r["k"]: r["mean(v)"] for r in ds.groupby("k").mean("v").take_all()}
    assert abs(means[0] - 13.5) < 1e-9
    mins = {r["k"]: r["min(v)"] for r in ds.groupby("k").min("v").take_all()}
    assert mins == {0: 0.0, 1: 1.0, 2: 2.0}
    maxs = {r["k"]: r["max(v)"] for r in ds.groupby("k").max("v").take_all()}
    assert maxs == {0: 27.0, 1: 28.0, 2: 29.0}


def test_groupby_map_groups(cluster):
    rows = [{"k": i % 2, "v": float(i)} for i in range(10)]
    ds = rd.from_items(rows)

    def summarize(group):
        return {"k": group["k"][:1], "n": np.asarray([len(group["v"])])}

    out = ds.groupby("k").map_groups(summarize).take_all()
    assert {int(r["k"]): int(r["n"]) for r in out} == {0: 5, 1: 5}


def test_sort(cluster):
    import random

    vals = list(range(200))
    random.Random(7).shuffle(vals)
    ds = rd.from_items([{"x": v} for v in vals])
    out = [int(r["x"]) for r in ds.sort("x").take_all()]
    assert out == sorted(vals)
    out_desc = [int(r["x"]) for r in ds.sort("x", descending=True).take_all()]
    assert out_desc == sorted(vals, reverse=True)


def test_zip_and_union(cluster):
    a = rd.from_items([{"x": i} for i in range(10)])
    b = rd.from_items([{"y": i * 2} for i in range(10)])
    z = a.zip(b).take_all()
    assert all(int(r["y"]) == 2 * int(r["x"]) for r in z)
    u = a.union(a)
    assert u.count() == 20


def test_actor_pool_map_batches(cluster):
    """Stateful map on an actor pool: the class is constructed once per
    pool actor (expensive state amortizes), not once per block."""

    class AddOffset:
        def __init__(self, offset):
            self.offset = offset
            self.calls = 0

        def __call__(self, block):
            self.calls += 1
            return {"value": block["value"] + self.offset}

    ds = rd.range(100, block_size=10).map_batches(
        AddOffset,
        compute=rd.ActorPoolStrategy(size=2),
        fn_constructor_args=(1000,),
    )
    out = sorted(int(v) for b in ds.iter_batches(batch_size=None) for v in b["value"])
    assert out == [i + 1000 for i in range(100)]


def test_actor_pool_requires_class(cluster):
    with pytest.raises(ValueError, match="callable CLASS"):
        rd.range(10).map_batches(lambda b: b, compute=rd.ActorPoolStrategy(size=1))


def test_groupby_multiblock_string_keys(cluster):
    """Keys hashed in DIFFERENT worker processes must land in the same
    partition (deterministic hash, not the process-salted builtin)."""
    words = (["alpha"] * 7 + ["beta"] * 5 + ["gamma"] * 3) * 4
    ds = rd.from_items([{"w": w} for w in words]).repartition(6)
    out = {r["w"]: int(r["count()"]) for r in ds.groupby("w").count().take_all()}
    assert out == {"alpha": 28, "beta": 20, "gamma": 12}


def test_shuffle_is_distributed_exchange(cluster):
    """random_shuffle must not concatenate the dataset on the driver:
    the result is produced by reduce tasks (refs), deterministic under a
    seed, and a real permutation."""
    ds = rd.range(512).random_shuffle(seed=3)
    vals = [int(v) for b in ds.iter_batches(batch_size=None) for v in b["value"]]
    assert sorted(vals) == list(range(512))
    assert vals != list(range(512))  # actually shuffled
    # deterministic for a fixed seed + block structure
    vals2 = [
        int(v)
        for b in rd.range(512).random_shuffle(seed=3).iter_batches(batch_size=None)
        for v in b["value"]
    ]
    assert vals == vals2


def test_write_parquet_roundtrip(cluster, tmp_path):
    out = str(tmp_path / "pq")
    ds = rd.range(100).map(lambda x: {"a": int(x), "b": float(x) * 0.5})
    files = ds.write_parquet(out)
    assert files and all(f.endswith(".parquet") for f in files)
    back = rd.read_parquet(out)
    rows = sorted(
        (int(b["a"][i]), float(b["b"][i]))
        for b in back.iter_batches(batch_size=None)
        for i in range(len(b["a"]))
    )
    assert rows == [(i, i * 0.5) for i in range(100)]


def test_write_csv_roundtrip(cluster, tmp_path):
    out = str(tmp_path / "csv")
    ds = rd.from_items([{"x": i, "y": i * 2} for i in range(20)])
    files = ds.write_csv(out)
    assert files
    back = rd.read_csv(out)
    rows = sorted(
        (int(b["x"][i]), int(b["y"][i]))
        for b in back.iter_batches(batch_size=None)
        for i in range(len(b["x"]))
    )
    assert rows == [(i, 2 * i) for i in range(20)]


def test_write_json_roundtrip(cluster, tmp_path):
    out = str(tmp_path / "json")
    ds = rd.from_items([{"k": i} for i in range(10)])
    ds.write_json(out)
    back = rd.read_json(out)
    vals = sorted(
        int(b["k"][i])
        for b in back.iter_batches(batch_size=None)
        for i in range(len(b["k"]))
    )
    assert vals == list(range(10))


def test_custom_datasink_lifecycle(cluster, tmp_path):
    """Datasink hooks run driver-side around per-block write tasks
    (reference datasink.py:51)."""
    marker = tmp_path / "started"

    class CollectSink(rd.Datasink):
        def __init__(self, base):
            self.base = str(base)

        def on_write_start(self):
            import pathlib

            pathlib.Path(self.base).mkdir(exist_ok=True)
            (pathlib.Path(self.base) / "started").touch()

        def write(self, block, ctx):
            return int(sum(int(v) for v in block["value"]))

        def on_write_complete(self, results):
            self.total = sum(results)

    sink = CollectSink(tmp_path / "sink")
    rd.range(64).write_datasink(sink)
    assert (tmp_path / "sink" / "started").exists()
    assert sink.total == sum(range(64))


def test_custom_datasource(cluster):
    class Squares(rd.Datasource):
        def get_read_tasks(self, parallelism):
            def make(i):
                return lambda: {"sq": np.arange(i * 10, (i + 1) * 10) ** 2}
            return [make(i) for i in range(4)]

    ds = rd.read_datasource(Squares())
    vals = sorted(
        int(v) for b in ds.iter_batches(batch_size=None) for v in b["sq"]
    )
    assert vals == sorted(int(i) ** 2 for i in range(40))


def test_write_numpy_roundtrip(cluster, tmp_path):
    out = str(tmp_path / "np")
    ds = rd.from_items([{"a": i, "b": i * 3} for i in range(30)])
    files = ds.write_numpy(out)
    assert files
    back = rd.read_numpy(out)
    rows = sorted(
        (int(b["a"][i]), int(b["b"][i]))
        for b in back.iter_batches(batch_size=None)
        for i in range(len(b["a"]))
    )
    assert rows == [(i, 3 * i) for i in range(30)]
