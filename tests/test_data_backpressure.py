"""Store-keyed admission control: an oversized dataset must stream
through a capacity-limited store without OOM or deadlock (reference
``backpressure_policy/`` + spilling)."""

import numpy as np
import pytest

import ray_tpu
import ray_tpu.data as rd


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=32 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_oversized_dataset_streams_through_small_store(cluster):
    """A dataset ~10x the object store streams through without OOM or
    deadlock: admission control pauses while the store is above its
    spill threshold, spilling covers the rest."""
    block_mb = 4
    n_blocks = 24  # ~96 MB total through a much smaller store

    def make_reader(i):
        def read():
            return {"value": np.full((block_mb << 20) // 8, i, dtype=np.int64)}
        return read

    from ray_tpu.data.dataset import Dataset

    ds = Dataset([make_reader(i) for i in range(n_blocks)]).map_batches(
        lambda b: {"value": b["value"][:1]}
    )
    seen = sorted(int(b["value"][0]) for b in ds.iter_batches(batch_size=None))
    assert seen == list(range(n_blocks))


@pytest.mark.slow
def test_oversized_shuffle_streams_through_small_store(cluster):
    """The distributed shuffle exchange moves a store-oversized dataset
    entirely through tasks + the object store (driver holds refs only);
    spilling absorbs the partition working set (reference push-based
    shuffle, exchange scheduler)."""
    block_mb = 3
    n_blocks = 20  # ~60 MB through a 32 MB store

    def make_reader(i):
        def read():
            rows = (block_mb << 20) // 16
            return {
                "key": np.full(rows, i, dtype=np.int64),
                "payload": np.arange(rows, dtype=np.int64),
            }
        return read

    from ray_tpu.data.dataset import Dataset

    ds = Dataset([make_reader(i) for i in range(n_blocks)])
    shuffled = ds.random_shuffle(seed=7)
    # every input row survives the exchange exactly once
    total = 0
    key_counts = {}
    for b in shuffled.iter_batches(batch_size=None):
        total += len(b["key"])
        for k, c in zip(*np.unique(b["key"], return_counts=True)):
            key_counts[int(k)] = key_counts.get(int(k), 0) + int(c)
    rows_per_block = (block_mb << 20) // 16
    assert total == n_blocks * rows_per_block
    assert key_counts == {i: rows_per_block for i in range(n_blocks)}
