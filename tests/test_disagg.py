"""ISSUE 13: disaggregated prefill/decode serving — cross-replica
KV-cache migration over the zero-copy data plane.

Acceptance gates covered here:

* **byte-exact handoff** — a temperature>0 request prefilled on the
  prefill pool and decoded on the decode pool yields the IDENTICAL
  token sequence to the same request run end-to-end on one engine
  (deterministic continuation makes the handoff exact by construction),
  with the migration provably used (decode-replica prefix hit +
  ``raytpu_kv_migration_transfers_total``);
* **failure → fallback ladder** — a corrupted descriptor (digest
  mismatch) degrades to a plain full prefill with the fallback counted,
  never a wrong or failed stream;
* **seeded replica chaos** — ``kill_mid_export`` on the prefill replica
  and ``kill_mid_import`` on the decode replica (the new
  ``ReplicaFaultPlan`` consult points): every stream stays byte-exact
  vs the undisturbed single-engine reference, zero client errors,
  fallback counter > 0 for the export kill, and the fault schedule
  replays deterministically from the logged seed;
* **radix-spine gossip** — the compacted ``prefix_digest`` export keeps
  ancestor chains intact under budget (the satellite's contract).
"""

import threading
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve

pytest.importorskip("jax")

import jax  # noqa: E402

from ray_tpu.inference.engine import EngineConfig, InferenceEngine  # noqa: E402
from ray_tpu.models.llama import LlamaConfig, init_params  # noqa: E402

#: 24 tokens = 3 full blocks at block_size 8 — enough to migrate, small
#: enough that every test stays CI-cheap
PROMPT = [5, 9, 2, 7, 1, 3, 8, 4] * 3

CHAOS_SEED = 1307


def _engine_cfg():
    # warmup=False + minimal buckets: every replica incarnation (and
    # the chaos tests spawn replacements) compiles only the programs a
    # request actually uses — the suite-runtime budget matters more
    # here than the zero-recompile property (asserted elsewhere)
    return EngineConfig(
        num_blocks=64, block_size=8, prefill_buckets=(8, 32),
        decode_buckets=(1, 2), max_decode_batch=2,
        max_new_tokens_default=8, warmup=False,
    )


@pytest.fixture(scope="module")
def disagg_handle():
    ray_tpu.init(num_cpus=4)
    dep = serve.llm_deployment(
        LlamaConfig.tiny(), engine=_engine_cfg(), name="dllm",
        disaggregated=True, prefill_replicas=1, decode_replicas=1,
        route_prefix="/dllm", ray_actor_options={"num_cpus": 0.25},
    )
    handle = serve.run(dep.bind())
    yield handle
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture(scope="module")
def reference_engine():
    """Undisturbed single-engine reference: same params seed + engine
    config as every replica, so identical requests must produce
    identical tokens."""
    cfg = LlamaConfig.tiny()
    eng = InferenceEngine(
        cfg, init_params(cfg, jax.random.PRNGKey(0)), _engine_cfg()
    ).start()
    yield eng
    eng.stop()


def _controller():
    return ray_tpu.get_actor("__serve_controller__")


def _replicas(name):
    return ray_tpu.get(_controller().get_replicas.remote(name), timeout=30)


def _replica_metrics(replica) -> str:
    addr = ray_tpu.get(
        replica.handle_request.remote("metrics_address", [], {}, ""),
        timeout=60,
    )
    return urllib.request.urlopen(
        f"http://{addr}/metrics", timeout=10
    ).read().decode()


def _stream(handle, req, timeout=120):
    return list(handle.stream(dict(req), _method="generate", _timeout=timeout))


# ---------------------------------------------------------------------------
# happy path


def test_byte_exact_handoff_with_sampling(disagg_handle, reference_engine):
    """The acceptance gate: prefill on replica pool A, decode on pool B,
    token stream identical to one engine doing both — at temperature>0,
    where any handoff drift (lost positions, re-seeded sampling, partial
    KV) would fork the stream immediately."""
    req = {
        "prompt": PROMPT, "max_new_tokens": 8,
        "temperature": 0.8, "seed": 42,
    }
    out = _stream(disagg_handle, req)
    ref = list(
        reference_engine.generate(
            PROMPT, max_new_tokens=8, temperature=0.8, seed=42
        )
    )
    assert out == ref, (out, ref)

    # the migration was USED, not silently fallen back from: the decode
    # replica admitted the request as a prefix hit over imported blocks
    # (23 of 24 prompt tokens skipped; the COW tail recomputed one)
    decode = _replicas("dllm")[0]
    stats = ray_tpu.get(
        decode.handle_request.remote("engine_stats", [], {}, ""), timeout=60
    )
    ps = stats["prefix_cache"]
    assert ps["hits_total"] >= 1, ps
    assert ps["tokens_saved_total"] >= len(PROMPT) - 1, ps
    body = _replica_metrics(decode)
    transfers = [
        float(line.rsplit(" ", 1)[1])
        for line in body.splitlines()
        if line.startswith("raytpu_kv_migration_transfers_total ")
    ]
    assert transfers and transfers[0] >= 1, transfers
    # and the prefill pool actually ran the prompt's prefill
    prefill = _replicas("dllm-prefill")[0]
    pstats = ray_tpu.get(
        prefill.handle_request.remote("engine_stats", [], {}, ""), timeout=60
    )
    assert pstats["scheduler"]["total_admitted"] >= 1
    # router-side handoff latency was observed (driver-process registry)
    from ray_tpu.inference.kv_transfer import migration_metrics

    hist = migration_metrics()["handoff"]
    assert sum(ent[-1] for ent in hist._values.values()) >= 1  # noqa: SLF001


def test_greedy_handoff_matches_and_reuses_radix(disagg_handle, reference_engine):
    """Greedy decode across the handoff, twice: the second request's
    prefill-pool export is near-free (its own radix cache) and the
    decode pool hits the already-imported blocks."""
    req = {"prompt": PROMPT, "max_new_tokens": 6}
    out1 = _stream(disagg_handle, req)
    out2 = _stream(disagg_handle, req)
    ref = list(reference_engine.generate(PROMPT, max_new_tokens=6))
    assert out1 == ref and out2 == ref, (out1, out2, ref)


# ---------------------------------------------------------------------------
# failure → fallback ladder


def test_digest_mismatch_falls_back_to_plain_prefill(disagg_handle, reference_engine):
    """A descriptor whose payload fails the digest-before-attach gate
    must degrade to a full prefill — correct tokens, counted fallback,
    no stream error."""
    prefill = _replicas("dllm-prefill")[0]
    desc = ray_tpu.get(
        prefill.handle_request.remote(
            "prefill_export",
            [{"prompt": PROMPT, "request_id": "corrupt.pf"}], {}, "",
        ),
        timeout=120,
    )
    assert desc is not None
    desc = dict(desc)
    desc["crc32"] = (desc["crc32"] ^ 0xFF) & 0xFFFFFFFF
    decode = _replicas("dllm")[0]
    out = ray_tpu.get(
        decode.handle_request.remote(
            "__call__",
            [{"prompt": PROMPT, "max_new_tokens": 4, "kv_import": desc}],
            {}, "",
        ),
        timeout=120,
    )
    ref = list(reference_engine.generate(PROMPT, max_new_tokens=4))
    assert out["tokens"] == ref
    body = _replica_metrics(decode)
    assert 'raytpu_kv_migration_fallbacks_total{reason="transfer"}' in body
    assert 'raytpu_kv_migration_failures_total{stage="digest"}' in body


def test_short_prompt_skips_migration(disagg_handle, reference_engine):
    """Prompts under serve_disagg_min_prompt_tokens never pay the
    handoff — counted as a short_prompt fallback, stream still exact."""
    from ray_tpu.inference.kv_transfer import migration_metrics

    fallbacks = migration_metrics()["fallbacks"]
    before = fallbacks._values.get(("short_prompt",), 0.0)  # noqa: SLF001
    req = {"prompt": [3, 1, 4], "max_new_tokens": 4}
    out = _stream(disagg_handle, req)
    ref = list(reference_engine.generate([3, 1, 4], max_new_tokens=4))
    assert out == ref
    assert fallbacks._values.get(("short_prompt",), 0.0) > before  # noqa: SLF001


# ---------------------------------------------------------------------------
# seeded replica chaos (the new export/import consult points)


def test_chaos_kill_prefill_mid_export_degrades_gracefully(
    disagg_handle, reference_engine
):
    """SIGKILL the prefill replica at its export consult: every stream
    must complete byte-exact via the fallback ladder (handoff fails →
    plain generation on the decode pool), zero client errors, fallback
    counter advanced, and the controller replaces the dead replica."""
    from ray_tpu.core.config import GLOBAL_CONFIG
    from ray_tpu.inference.kv_transfer import migration_metrics

    prefill = _replicas("dllm-prefill")[0]
    ray_tpu.get(
        prefill.handle_request.remote(
            "testing_arm_replica_chaos",
            ["kill_mid_export:1.0", CHAOS_SEED], {}, "",
        ),
        timeout=60,
    )
    fallbacks = migration_metrics()["fallbacks"]
    before = sum(fallbacks._values.values())  # noqa: SLF001
    old_timeout = GLOBAL_CONFIG.serve_disagg_handoff_timeout_s
    # the replacement replica is unarmed, so an unbounded handoff budget
    # would eventually succeed via retry; a tight budget pins the
    # fallback rung this test asserts (production keeps the retry)
    GLOBAL_CONFIG.serve_disagg_handoff_timeout_s = 2.0
    try:
        n = 3
        results, errors = {}, {}

        def consume(i):
            try:
                results[i] = _stream(
                    disagg_handle,
                    {
                        "prompt": PROMPT, "max_new_tokens": 6,
                        "temperature": 0.7, "seed": 100 + i,
                    },
                )
            except Exception as e:  # noqa: BLE001
                errors[i] = e

        threads = [
            threading.Thread(target=consume, args=(i,)) for i in range(n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        for i in range(n):
            ref = list(
                reference_engine.generate(
                    PROMPT, max_new_tokens=6, temperature=0.7, seed=100 + i
                )
            )
            assert results[i] == ref, (i, results[i], ref)
        assert sum(fallbacks._values.values()) > before  # noqa: SLF001
    finally:
        GLOBAL_CONFIG.serve_disagg_handoff_timeout_s = old_timeout
    # the controller replaces the killed prefill replica
    st = ray_tpu.get(
        _controller().wait_status.remote(
            "dllm-prefill", min_replicas=1, timeout_s=90
        ),
        timeout=120,
    )
    assert st["replicas"] >= 1, st


def test_chaos_kill_decode_mid_import_resumes_byte_exact(
    disagg_handle, reference_engine
):
    """SIGKILL the decode replica at its import consult: the stream dies
    before its first token and the PR 10 resumable-stream machinery
    replays it (descriptor stripped) on the replacement — byte-exact,
    zero client errors."""
    # the prefill pool must be healthy again after the previous test
    ray_tpu.get(
        _controller().wait_status.remote(
            "dllm-prefill", min_replicas=1, timeout_s=90
        ),
        timeout=120,
    )
    decode = _replicas("dllm")[0]
    ray_tpu.get(
        decode.handle_request.remote(
            "testing_arm_replica_chaos",
            ["kill_mid_import:1.0", CHAOS_SEED + 1], {}, "",
        ),
        timeout=60,
    )
    out = _stream(
        disagg_handle,
        {
            "prompt": PROMPT, "max_new_tokens": 6,
            "temperature": 0.9, "seed": 777,
        },
        timeout=180,
    )
    ref = list(
        reference_engine.generate(
            PROMPT, max_new_tokens=6, temperature=0.9, seed=777
        )
    )
    assert out == ref, (out, ref)
    st = ray_tpu.get(
        _controller().wait_status.remote("dllm", min_replicas=1, timeout_s=90),
        timeout=120,
    )
    assert st["replicas"] >= 1, st


def test_fault_schedule_replays_from_seed():
    """The determinism contract the chaos tests lean on: one RNG draw
    per consult ⇒ the injection schedule is a pure function of (seed,
    consulted-phase sequence) — a failure log carrying the seed replays
    the exact run."""
    from ray_tpu.util.chaos import ReplicaFaultPlan

    phases = ["prefill", "export", "decode", "import", "export", "decode"]
    spec = "kill_mid_export:0.5:0:3,kill_mid_import:0.5:0:3"

    def schedule():
        plan = ReplicaFaultPlan(spec, CHAOS_SEED)
        return [plan.consult(p) for p in phases]

    assert schedule() == schedule()
    assert any(f is not None for f in schedule())  # the seed does inject


# ---------------------------------------------------------------------------
# radix-spine gossip (digest compaction satellite)


def test_prefix_digest_exports_complete_spines_under_budget():
    """Under a budget smaller than the index, the gossip export must
    consist of root-anchored chains (every exported digest's ancestors
    exported with it) — the consecutive-prefix matcher can't use
    orphans. The old flat recent-N slice violated exactly this."""
    from ray_tpu.inference.kv_cache import (
        PagedBlockManager,
        prefix_block_hashes,
    )

    bs = 4
    mgr = PagedBlockManager(64, bs, prefix_cache_enabled=True)
    # two chains: a deep "hot path" (4 blocks) and a shallow one (2)
    deep = list(range(100, 116))   # 16 tokens = 4 blocks
    shallow = list(range(200, 208))  # 8 tokens = 2 blocks
    for rid, tokens in (("deep", deep), ("shallow", shallow)):
        assert mgr.grow_to(rid, len(tokens))
        mgr.register_prefix(rid, tokens)
        mgr.free(rid)
    full = mgr.prefix_digest()
    assert len(full) == 6
    deep_hashes = prefix_block_hashes(deep, bs)
    shallow_hashes = prefix_block_hashes(shallow, bs)
    # every exported entry is usable: for any exported digest, its whole
    # ancestor chain is in the export
    for budget in (2, 3, 4, 5, 6):
        out = set(mgr.prefix_digest(max_entries=budget))
        assert len(out) <= budget
        for chain in (deep_hashes, shallow_hashes):
            for i, h in enumerate(chain):
                if h in out:
                    assert all(a in out for a in chain[: i + 1]), (
                        budget, i, out,
                    )
    # budget 3 can't fit the 4-deep spine whole; it must still ship the
    # complete 2-chain (plus at most an ancestor-closed PREFIX of the
    # deep chain — a 1-block root spine is complete and usable), never
    # a truncated frontier of deep leaves
    out3 = set(mgr.prefix_digest(max_entries=3))
    assert set(shallow_hashes) <= out3
    deep_in = [h for h in deep_hashes if h in out3]
    assert deep_in == deep_hashes[: len(deep_in)], (deep_in, out3)


def test_delete_cascades_to_prefill_pool(disagg_handle):
    """serve.delete of a disaggregated deployment must tear down the
    paired prefill pool too — orphaned prefill replicas are full engines
    (params + KV cache) that would otherwise survive until a
    whole-controller shutdown. (Runs last: it deletes the module
    fixture's deployment.)"""
    assert "dllm-prefill" in serve.status()
    serve.delete("dllm")
    st = serve.status()
    assert "dllm" not in st and "dllm-prefill" not in st, st
