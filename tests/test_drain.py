"""Graceful node drain (PR 2): the preemption-aware drain protocol.

A drained node leaves the scheduling pool, finishes running work within
the grace, replicates primary object copies off-node, deregisters, and
exits cleanly; actor restarts it causes consume no ``max_restarts``
budget; Train takes an urgent checkpoint on the warning; Serve hands
traffic off with zero client-visible errors. ``PreemptionKiller``
delivers the real contract: SIGTERM warning, SIGKILL after the grace.

Suite-time relief (ROADMAP CAUTION): ONE module-scoped cluster; every
test adds its own sacrificial node under a test-UNIQUE resource name and
drains/kills only that node, so leftover replacement capacity from an
earlier test can never host a later test's pinned work. The module
cluster runs with ``drain_grace_s=3.0`` (set BEFORE the head spawns so
every daemon inherits it): a plain actor never exits on its own, so
actor-hosting drains wait the full grace — 3s keeps that fast without
changing the semantics under test.
"""

import os
import signal
import time

import pytest

import ray_tpu
from conftest import wait_for_node_resource
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.chaos import PreemptionKiller


def _wait(pred, timeout=60, msg=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.2)
    raise AssertionError(f"timed out: {msg}")


def _node_rows():
    return {n["NodeID"]: n for n in ray_tpu.nodes()}


@pytest.fixture(scope="module")
def drain_cluster():
    from ray_tpu.core.config import GLOBAL_CONFIG

    old_grace = GLOBAL_CONFIG.drain_grace_s
    old_health = GLOBAL_CONFIG.health_check_period_s
    old_thresh = GLOBAL_CONFIG.health_check_failure_threshold
    GLOBAL_CONFIG.drain_grace_s = 3.0
    # SIGKILLed nodes (grace-expiry, preemption tests) are detected via
    # the health loop: staleness window (period×threshold) + threshold
    # failed pings. 0.5s×3 cuts detection from ~5-6s to ~3s per kill
    # without changing the two-stage semantics under test.
    GLOBAL_CONFIG.health_check_period_s = 0.5
    GLOBAL_CONFIG.health_check_failure_threshold = 3
    cluster = Cluster(num_cpus=2)
    ray_tpu.init(address=cluster.address)
    try:
        yield cluster
    finally:
        GLOBAL_CONFIG.drain_grace_s = old_grace
        GLOBAL_CONFIG.health_check_period_s = old_health
        GLOBAL_CONFIG.health_check_failure_threshold = old_thresh
        ray_tpu.shutdown()
        cluster.shutdown()


def test_maintenance_event_probe_is_pluggable():
    """The preemption probe reads the injectable metadata fetcher — the
    daemon's probe loop (preemption_probe_period_s) drains on exactly
    this signal, so non-GCE deployments plug in their own."""
    from ray_tpu.accelerators import tpu as tpu_mod

    try:
        tpu_mod.set_metadata_fetcher(
            lambda path: "NONE" if path == tpu_mod.MAINTENANCE_EVENT_PATH else None
        )
        assert not tpu_mod.maintenance_event_imminent()
        tpu_mod.set_metadata_fetcher(lambda path: "TERMINATE_ON_HOST_MAINTENANCE")
        assert tpu_mod.maintenance_event_imminent()
        assert (
            tpu_mod.get_current_node_maintenance_event()
            == "TERMINATE_ON_HOST_MAINTENANCE"
        )
        tpu_mod.set_metadata_fetcher(lambda path: None)  # no metadata server
        assert not tpu_mod.maintenance_event_imminent()
    finally:
        tpu_mod.set_metadata_fetcher(None)


def test_drain_excludes_node_from_scheduling(drain_cluster):
    """A DRAINING node stops receiving new tasks; it deregisters and its
    daemon exits 0 once idle (clean-exit half of the drain contract)."""
    n2 = drain_cluster.add_node(num_cpus=4, resources={"excl": 4})
    wait_for_node_resource("excl")

    @ray_tpu.remote(num_cpus=0.5)
    def where():
        return ray_tpu.get_runtime_context().get_node_id()

    # warm up: reach the pinned node at least once
    nid2 = None
    for _ in range(4):
        nid = ray_tpu.get(where.options(resources={"excl": 1}).remote(), timeout=60)
        nid2 = nid
    assert nid2 is not None
    assert ray_tpu.drain_node(nid2, "test: scheduling exclusion")
    # the daemon drains (idle) and deregisters: entry goes DEAD, no
    # ghost DRAINING row, process exits 0
    _wait(
        lambda: _node_rows()[nid2]["State"] == "DEAD",
        timeout=30,
        msg="drained node should deregister to DEAD",
    )
    _wait(lambda: n2.poll() is not None, timeout=20, msg="daemon should exit")
    assert n2.poll() == 0, f"drain exit code {n2.poll()}"
    # new work must not land there (it CAN'T — node gone); spillback
    # and scheduling keep working on the survivors
    spots = set(ray_tpu.get([where.remote() for _ in range(8)], timeout=120))
    assert nid2 not in spots


def test_drained_actor_restart_consumes_no_budget(drain_cluster):
    """Actor restarts caused by a drain are budget-free: a max_restarts=1
    actor survives a drain AND still has its one crash-restart left.
    (Module grace is 3.0s: a plain actor never exits on its own, so the
    drain waits the full grace before deregistering.)"""
    drain_cluster.add_node(num_cpus=2, resources={"p3": 2})
    host_raw = wait_for_node_resource("p3")
    host_nid = host_raw.hex() if isinstance(host_raw, bytes) else host_raw

    @ray_tpu.remote(max_restarts=1, max_task_retries=4, num_cpus=0, resources={"p3": 1})
    class A:
        def pid(self):
            return os.getpid()

        def node(self):
            return ray_tpu.get_runtime_context().get_node_id()

    a = A.remote()
    pid1 = ray_tpu.get(a.pid.remote(), timeout=120)
    nid = ray_tpu.get(a.node.remote(), timeout=60)
    assert nid == host_nid
    # replacement capacity first, then drain the hosting node
    drain_cluster.add_node(num_cpus=2, resources={"p3": 2})
    wait_for_node_resource("p3", exclude={host_raw})
    assert ray_tpu.drain_node(nid, "test: budget-free restart")
    _wait(
        lambda: _node_rows()[nid]["State"] == "DEAD",
        timeout=40,
        msg="drained node deregisters",
    )
    deadline = time.time() + 90
    pid2 = None
    while time.time() < deadline:
        try:
            pid2 = ray_tpu.get(a.pid.remote(), timeout=15)
            break
        except ray_tpu.RayTpuError:
            time.sleep(1)
    assert pid2 is not None and pid2 != pid1
    # the drain restart consumed NO budget
    from ray_tpu.core.api import _global_worker

    be = _global_worker().backend
    info = be.io.run(
        be.controller.call("get_actor_info", {"actor_id": a.actor_id})
    )
    assert info["num_restarts"] == 0, info
    # the one real crash-restart is still available
    os.kill(pid2, signal.SIGKILL)
    deadline = time.time() + 90
    pid3 = None
    while time.time() < deadline:
        try:
            pid3 = ray_tpu.get(a.pid.remote(), timeout=15)
            break
        except ray_tpu.RayTpuError:
            time.sleep(1)
    assert pid3 is not None and pid3 != pid2
    info = be.io.run(
        be.controller.call("get_actor_info", {"actor_id": a.actor_id})
    )
    assert info["num_restarts"] == 1, info


def test_drain_flushes_objects_off_node(drain_cluster):
    """Primary copies on a drained node are replicated to a peer and
    remain gettable afterwards WITHOUT lineage reconstruction (the
    producing task cannot re-run: it was a one-shot put). INLINE results
    take the opposite path: they never enter the relocation machinery —
    the directory holds nothing for them and is never consulted; get()
    answers from the owner-side inline cache after the node is gone."""
    n2 = drain_cluster.add_node(num_cpus=2, resources={"p4": 2})
    nid = wait_for_node_resource("p4")

    @ray_tpu.remote(num_cpus=0, resources={"p4": 1}, max_retries=0)
    def big_block(i):
        # large enough to live in shm (not inlined in the reply)
        return bytes([i]) * (512 * 1024)

    @ray_tpu.remote(num_cpus=0, resources={"p4": 1}, max_retries=0)
    def small(i):
        return bytes([i]) * 64  # inline: rides back in the reply

    refs = [big_block.remote(i) for i in range(4)]
    inline_refs = [small.remote(i) for i in range(4)]
    ray_tpu.wait(
        refs + inline_refs,
        num_returns=len(refs) + len(inline_refs),
        timeout=120,
        fetch_local=False,
    )
    assert ray_tpu.drain_node(nid, "test: object flush")
    _wait(lambda: n2.poll() is not None, timeout=40, msg="daemon exits")
    # max_retries=0: lineage reconstruction is OFF for these tasks —
    # only the drain-time replication can satisfy these gets
    vals = ray_tpu.get(refs, timeout=120)
    assert [v[:1] for v in vals] == [bytes([i]) for i in range(4)]
    assert all(len(v) == 512 * 1024 for v in vals)
    # inline results: nothing was replicated for these ids…
    from ray_tpu.core.api import _global_worker

    core = _global_worker().backend
    for r in inline_refs:
        assert (
            core.io.run(
                core.controller.call(
                    "get_relocated", {"object_id": r.id().binary()}, timeout=10
                )
            )
            is None
        )

    def relocated_consults():
        stats = core.io.run(core.controller.call("event_stats", None, timeout=10))
        return stats["handlers"].get("get_relocated", {}).get("count", 0)

    # …and their gets are served from the owner inline cache without
    # a single relocation-directory consult
    before = relocated_consults()
    assert ray_tpu.get(inline_refs, timeout=60) == [
        bytes([i]) * 64 for i in range(4)
    ]
    assert relocated_consults() == before


def test_preemption_mid_training_resumes_from_urgent_checkpoint(drain_cluster):
    """End-to-end chaos: a PreemptionKiller takes out the training node
    (warning → SIGKILL after grace) mid-run; the warning triggers an
    urgent checkpoint, the AUTOSCALER provisions the replacement (a
    DRAINING node counts as unmet demand, and a fully-draining launch
    group stops counting against max_workers), the gang restarts there,
    and the run completes having lost no more than steps-since-warning.
    (The gang needs the autoscaler-only "trainer" resource, so leftover
    sacrificial nodes from earlier tests can never host it.)"""
    from ray_tpu.autoscaler import (
        AutoscalerConfig,
        FakeMultiNodeProvider,
        NodeTypeConfig,
        StandardAutoscaler,
    )
    from ray_tpu.core.config import GLOBAL_CONFIG

    # autoscaled boot can outrun the default infeasible patience on a
    # loaded box (same deflake as test_autoscaler.py)
    old_patience = GLOBAL_CONFIG.infeasible_fail_after_s
    GLOBAL_CONFIG.infeasible_fail_after_s = 90.0
    provider = FakeMultiNodeProvider(
        f"127.0.0.1:{drain_cluster.controller_port}"
    )
    autoscaler = StandardAutoscaler(
        provider,
        AutoscalerConfig(
            node_types=[
                NodeTypeConfig("trainer", {"CPU": 2, "trainer": 2}, max_workers=1)
            ],
            idle_timeout_s=120.0,
            update_interval_s=0.3,
        ),
    )
    autoscaler.start()
    try:
        from ray_tpu import train
        from ray_tpu.train import (
            FailureConfig,
            JaxTrainer,
            RunConfig,
            ScalingConfig,
        )

        def train_fn(config):
            w = 0.0
            start = 0
            ckpt = train.get_checkpoint()
            if ckpt is not None:
                state = ckpt.to_dict()
                w, start = state["w"], state["step"]
            for step in range(start, 14):
                time.sleep(0.4)
                w += 1.0
                # checkpoint cadence: ONLY when the preemption warning
                # lands (urgent), plus one periodic at step 2 — so a
                # resume past step 2 proves the urgent path worked
                urgent = train.urgent_checkpoint_requested()
                if urgent or step == 1:
                    train.report(
                        {"w": w, "step": step + 1, "urgent": urgent},
                        checkpoint=train.Checkpoint.from_dict(
                            {"w": w, "step": step + 1}
                        ),
                    )
                else:
                    train.report({"w": w, "step": step + 1})
            train.report({"w": w, "step": 14})

        trainer = JaxTrainer(
            train_fn,
            train_loop_config={},
            scaling_config=ScalingConfig(
                num_workers=1,
                resources_per_worker={"CPU": 1, "trainer": 1},
            ),
            run_config=RunConfig(
                name=f"drain-train-{os.getpid()}-{int(time.time() * 1000)}",
                failure_config=FailureConfig(max_failures=3),
            ),
        )
        killer = PreemptionKiller(drain_cluster, grace_s=4.0)

        import threading

        fired = threading.Event()

        def preempt_later():
            # wait until the autoscaler has launched the training node
            # and training is past the periodic checkpoint at step 2
            deadline = time.time() + 90
            while time.time() < deadline and not provider.non_terminated_nodes():
                time.sleep(0.2)
            time.sleep(6.0)
            rec = next(iter(provider._nodes.values()), None)
            if rec is not None:
                killer.preempt(rec["proc"])
            fired.set()

        t = threading.Thread(target=preempt_later, daemon=True)
        t.start()
        result = trainer.fit()
        t.join(timeout=120)
        assert fired.is_set()
        assert killer.kills == 1, "preemption never fired"
        assert result.metrics["w"] == 14.0
        # the AUTOSCALER provisioned the replacement (second launch of a
        # max_workers=1 type: only possible because the draining group
        # stopped counting against the cap)
        assert provider._seq >= 2, "autoscaler never replaced the node"
        # the resume point must come from the URGENT checkpoint (past the
        # step-2 periodic one): some report carried urgent=True
        urgents = [m for m in result.metrics_history if m.get("urgent")]
        assert urgents, (
            "urgent checkpoint was never requested/taken: "
            f"{result.metrics_history}"
        )
    finally:
        autoscaler.stop()
        GLOBAL_CONFIG.infeasible_fail_after_s = old_patience
        provider.shutdown()


def test_serve_drain_zero_failed_requests(drain_cluster):
    """A replica's node is preempted (warning → SIGKILL) under a steady
    request stream: the drain handoff (unroute → finish in-flight →
    replacement) keeps every request answered — zero client errors."""
    n2 = drain_cluster.add_node(num_cpus=2, resources={"srv": 2})
    nid2 = wait_for_node_resource("srv")
    from ray_tpu import serve

    @serve.deployment(
        num_replicas=2,
        ray_actor_options={"num_cpus": 0.25, "resources": {"srv": 1}},
    )
    class Echo:
        def __call__(self, x):
            time.sleep(0.05)
            return x

    # one replica per "srv" slot: extra capacity so the drained
    # replica has somewhere to respawn
    drain_cluster.add_node(num_cpus=2, resources={"srv": 2})
    wait_for_node_resource("srv", exclude={nid2})
    handle = serve.run(Echo.bind())
    try:
        import threading

        results, errors = [], []
        stop = threading.Event()

        def hammer():
            i = 0
            while not stop.is_set():
                try:
                    results.append(handle.call(i, _timeout=60))
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                i += 1
                time.sleep(0.02)

        t = threading.Thread(target=hammer)
        t.start()
        try:
            time.sleep(0.5)
            killer = PreemptionKiller(drain_cluster, grace_s=5.0)
            killer.preempt(n2)  # blocks for the grace, then SIGKILLs
            # stream keeps flowing across the handoff + replacement
            time.sleep(2.0)
        finally:
            stop.set()
            t.join(timeout=60)
        assert not errors, errors[:3]
        # the stream must have actually spanned the preemption window
        # (~10s at 50ms/request + pacing — a stalled handoff would show
        # far fewer completions)
        assert len(results) > 20, len(results)
        # deployment healed back to 2 routed replicas
        st = ray_tpu.get(
            handle._controller.wait_status.remote(
                "Echo", min_replicas=2, quiescent=True, timeout_s=120
            ),
            timeout=150,
        )
        assert st and st["replicas"] == 2, st
    finally:
        serve.delete("Echo")
        serve.shutdown()


def test_drain_grace_expiry_falls_back_to_abrupt_death(drain_cluster):
    """A task that outlives the drain grace: the SIGKILL lands on a
    still-running node, the controller detects the death through the
    normal health-check path, and the task is retried elsewhere."""
    n2 = drain_cluster.add_node(num_cpus=2, resources={"stub": 2})
    stub_raw = wait_for_node_resource("stub")
    stub_nid = stub_raw.hex() if isinstance(stub_raw, bytes) else stub_raw

    @ray_tpu.remote(num_cpus=0.5, max_retries=2)
    def stubborn(path):
        # runs way past any drain grace the killer allows; the retry
        # (on a surviving node) finds the marker and returns fast
        if os.path.exists(path):
            return "retried"
        open(path, "w").close()
        time.sleep(300)
        return "finished"

    marker = f"/tmp/ray_tpu_drain_marker_{os.getpid()}"
    if os.path.exists(marker):
        os.unlink(marker)

    # pin the first execution to the doomed node
    ref = stubborn.options(resources={"stub": 1}).remote(marker)
    _wait(lambda: os.path.exists(marker), timeout=60, msg="task started")
    killer = PreemptionKiller(drain_cluster, grace_s=2.0)
    killer.preempt(n2)  # grace far shorter than the task: abrupt kill
    assert killer.kills == 1
    # retry must run somewhere else (the stub resource died with the
    # node) — drop the constraint by retrying through task retry:
    # the spec keeps its stub pin, so a replacement node supplies it
    drain_cluster.add_node(num_cpus=2, resources={"stub": 2})
    assert ray_tpu.get(ref, timeout=180) == "retried"
    # the abrupt-death half of the contract: the controller's health
    # check must flip the SIGKILLed (never-deregistered) DRAINING row
    # to DEAD — no ghost entry survives
    _wait(
        lambda: _node_rows()[stub_nid]["State"] == "DEAD",
        timeout=30,
        msg="killed draining node should be health-checked to DEAD",
    )
    if os.path.exists(marker):
        os.unlink(marker)
