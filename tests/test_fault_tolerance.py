"""Fault tolerance: node death detection + actor restart on a new node,
drain-vs-crash restart accounting, and pool-actor recovery in Data.

Suite-time note (ISSUE 14): one MODULE-scoped cluster instead of a full
cluster per test. Each node-failure test adds its own sacrificial
node(s) with test-unique resources, so a leftover replacement node from
an earlier test can never host a later test's pinned actor. The drain
grace is shortened for the WHOLE module (set before any daemon spawns so
it serializes into them): a plain actor never exits on its own, and the
drain would otherwise wait the full 30s before deregistering."""

import os
import signal
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

from conftest import wait_for_node_resource


@pytest.fixture(scope="module")
def ft_cluster():
    from ray_tpu.core.config import GLOBAL_CONFIG

    old_grace = GLOBAL_CONFIG.drain_grace_s
    GLOBAL_CONFIG.drain_grace_s = 3.0
    cluster = Cluster(num_cpus=4)
    time.sleep(0.5)
    ray_tpu.init(address=cluster.address)
    yield cluster
    GLOBAL_CONFIG.drain_grace_s = old_grace
    ray_tpu.shutdown()
    cluster.shutdown()


def test_node_death_actor_restart(ft_cluster):
    cluster = ft_cluster
    n2 = cluster.add_node(num_cpus=1, resources={"pin_nd": 1})
    nid = wait_for_node_resource("pin_nd")

    @ray_tpu.remote(max_restarts=1, resources={"pin_nd": 1}, num_cpus=0)
    class A:
        def pid(self):
            import os

            return os.getpid()

    a = A.remote()
    pid1 = ray_tpu.get(a.pid.remote(), timeout=120)
    cluster.remove_node(n2)
    cluster.add_node(num_cpus=1, resources={"pin_nd": 1})
    wait_for_node_resource("pin_nd", exclude={nid})
    deadline = time.time() + 90
    pid2 = None
    while time.time() < deadline:
        try:
            pid2 = ray_tpu.get(a.pid.remote(), timeout=15)
            break
        except ray_tpu.RayTpuError:
            time.sleep(1)
    assert pid2 is not None and pid2 != pid1


def _num_restarts(handle) -> int:
    from ray_tpu.core.api import _global_worker

    be = _global_worker().backend
    info = be.io.run(
        be.controller.call("get_actor_info", {"actor_id": handle.actor_id})
    )
    return info["num_restarts"]


def test_drain_vs_crash_restart_accounting(ft_cluster):
    """The SAME actor failover path, two causes: a node CRASH consumes
    max_restarts budget, a node DRAIN does not — preemption is not the
    actor's failure (reference: DrainNode restarts are budget-exempt)."""
    cluster = ft_cluster
    n_crash = cluster.add_node(num_cpus=1, resources={"crash": 1})
    cluster.add_node(num_cpus=1, resources={"drain": 1})
    crash_nid = wait_for_node_resource("crash")
    drain_nid0 = wait_for_node_resource("drain")

    @ray_tpu.remote(max_restarts=2, max_task_retries=4, num_cpus=0)
    class A:
        def pid(self):
            return os.getpid()

        def node(self):
            return ray_tpu.get_runtime_context().get_node_id()

    a_crash = A.options(resources={"crash": 1}).remote()
    a_drain = A.options(resources={"drain": 1}).remote()
    ray_tpu.get([a_crash.pid.remote(), a_drain.pid.remote()], timeout=120)
    drain_nid = ray_tpu.get(a_drain.node.remote(), timeout=60)
    # replacement capacity for both actors
    cluster.add_node(num_cpus=2, resources={"crash": 1, "drain": 1})
    wait_for_node_resource("crash", exclude={crash_nid})
    wait_for_node_resource("drain", exclude={drain_nid0})

    # crash path: hard node kill
    cluster.remove_node(n_crash)
    # drain path: graceful preemption
    assert ray_tpu.drain_node(drain_nid, "test: drain-vs-crash")

    def recovered(handle):
        deadline = time.time() + 90
        while time.time() < deadline:
            try:
                return ray_tpu.get(handle.pid.remote(), timeout=15)
            except ray_tpu.RayTpuError:
                time.sleep(1)
        return None

    assert recovered(a_crash) is not None
    assert recovered(a_drain) is not None
    assert _num_restarts(a_crash) == 1  # crash consumed budget
    assert _num_restarts(a_drain) == 0  # drain did not


def test_data_pool_actor_death_recovery(ft_cluster):
    """A Data actor-pool stage survives its pool actors being SIGKILLed
    mid-block: in-flight blocks resubmit to surviving/fresh actors and
    the stage completes with every block intact. (Rides the module
    cluster — the pool actors land wherever CPU is free; the SIGKILL is
    same-host either way.)"""
    from ray_tpu.data.executor import (
        ActorPoolStrategy,
        ActorStage,
        execute_actor_stage,
        execute_streaming,
    )

    class PidDouble:
        def __call__(self, block):
            time.sleep(0.2)
            return {"v": [x * 2 for x in block["v"]], "pid": [os.getpid()] * len(block["v"])}

    sources = [(lambda i=i: {"v": [i]}) for i in range(10)]
    upstream = execute_streaming(sources, [], max_inflight=10)
    stage = ActorStage(PidDouble, (), {}, ActorPoolStrategy(2))
    it = execute_actor_stage(upstream, stage)
    first = ray_tpu.get(next(it), timeout=120)
    # kill the worker that produced the first block — later in-flight
    # blocks on that actor must be resubmitted, not failed
    victim = int(first["pid"][0])
    os.kill(victim, signal.SIGKILL)
    rest = [ray_tpu.get(r, timeout=120) for r in it]
    got = sorted(int(b["v"][0]) for b in [first] + rest)
    assert got == [i * 2 for i in range(10)], got
    # at least one surviving/replacement actor finished the tail
    assert any(int(b["pid"][0]) != victim for b in rest)
