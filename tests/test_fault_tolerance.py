"""Fault tolerance: node death detection + actor restart on a new node."""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


def test_node_death_actor_restart():
    cluster = Cluster(num_cpus=1)
    n2 = cluster.add_node(num_cpus=1, resources={"pin": 1})
    time.sleep(1.0)
    ray_tpu.init(address=cluster.address)
    try:

        @ray_tpu.remote(max_restarts=1, resources={"pin": 1}, num_cpus=0)
        class A:
            def pid(self):
                import os

                return os.getpid()

        a = A.remote()
        pid1 = ray_tpu.get(a.pid.remote(), timeout=120)
        cluster.remove_node(n2)
        cluster.add_node(num_cpus=1, resources={"pin": 1})
        deadline = time.time() + 90
        pid2 = None
        while time.time() < deadline:
            try:
                pid2 = ray_tpu.get(a.pid.remote(), timeout=15)
                break
            except ray_tpu.RayTpuError:
                time.sleep(1)
        assert pid2 is not None and pid2 != pid1
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
