"""Hang-defense layer tests: event-loop stall watchdog, deadline
propagation, escalating process reaping, and leak-free chaos teardown.

Reference analogues: ``common/event_stats.h`` (instrumented handlers),
``GcsHealthCheckManager`` (liveness), and the SRE literature's core
claim (gray failure): a stall you cannot observe is a failure you
cannot recover from. These tests make the observation machinery itself
load-bearing.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.core.deadline import Deadline, deadline_scope, effective_timeout
from ray_tpu.util.reaper import find_runtime_pids, pid_alive, reap_process


# ---------------------------------------------------------------------------
# watchdog / event stats


def test_watchdog_detects_stall_and_names_blocking_frame():
    """A deliberately stalled event loop is detected within the threshold
    and the dump identifies the blocking handler (acceptance criterion)."""
    from ray_tpu.core.rpc import IoThread

    old_threshold = GLOBAL_CONFIG.event_loop_stall_threshold_s
    old_tick = GLOBAL_CONFIG.event_loop_tick_s
    GLOBAL_CONFIG.event_loop_stall_threshold_s = 0.3
    GLOBAL_CONFIG.event_loop_tick_s = 0.05
    io = None
    try:
        io = IoThread(name="wd-test-io")
        time.sleep(0.3)  # let the heartbeat start
        assert io.monitor is not None

        async def block_the_loop():
            time.sleep(1.5)  # synchronous sleep ON the loop = the bug class

        io.post(block_the_loop())
        # poll for the DUMP, not just the counter: the counter bumps a
        # beat before the dump text lands
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and not io.monitor.last_dump_text:
            time.sleep(0.05)
        assert io.monitor.stall_count >= 1, "stall never detected"
        dump = io.monitor.last_dump_text
        assert dump, "stall detected but no dump produced"
        assert "STALLED EVENT LOOP" in dump
        assert "block_the_loop" in dump, dump  # the blocking handler, by name
        assert "time.sleep" in dump, dump  # and the blocking frame itself
        # loop recovers after the handler returns: the late heartbeat
        # records the stall's magnitude in the lag gauge
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and io.monitor.max_lag_s < 1.0:
            time.sleep(0.05)
        assert io.monitor.max_lag_s >= 1.0
    finally:
        GLOBAL_CONFIG.event_loop_stall_threshold_s = old_threshold
        GLOBAL_CONFIG.event_loop_tick_s = old_tick
        if io is not None:
            io.stop()


def test_watchdog_hard_abort_in_test_mode(tmp_path):
    """watchdog_abort_after_s > 0: a persistently stalled process dumps
    stacks and hard-exits with the watchdog code instead of wedging."""
    script = tmp_path / "stall.py"
    script.write_text(
        "import time\n"
        "from ray_tpu.core.config import GLOBAL_CONFIG\n"
        "GLOBAL_CONFIG.event_loop_stall_threshold_s = 0.2\n"
        "GLOBAL_CONFIG.event_loop_tick_s = 0.05\n"
        "GLOBAL_CONFIG.watchdog_abort_after_s = 0.5\n"
        "from ray_tpu.core.rpc import IoThread\n"
        "io = IoThread(name='abort-io')\n"
        "time.sleep(0.3)\n"
        "async def wedge():\n"
        "    time.sleep(600)\n"
        "io.post(wedge())\n"
        "time.sleep(60)\n"
        "raise SystemExit(1)  # watchdog should have killed us long ago\n"
    )
    from ray_tpu.observability.event_stats import WATCHDOG_ABORT_EXIT_CODE

    env = dict(os.environ)
    env.pop("RAY_TPU_watchdog_abort_after_s", None)  # script sets its own
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
    proc = subprocess.run(
        [sys.executable, str(script)],
        env=env,
        capture_output=True,
        timeout=60,
    )
    assert proc.returncode == WATCHDOG_ABORT_EXIT_CODE, (
        proc.returncode,
        proc.stderr[-2000:],
    )
    assert b"wedge" in proc.stderr  # the dump names the stalled handler


def test_event_stats_record_handler_timing(ray_start_regular):
    """Every RPC dispatch lands in the per-process handler registry and
    the Prometheus series exist (reference event_stats.h exposition)."""
    from ray_tpu.core.api import _global_worker
    from ray_tpu.observability.event_stats import GLOBAL_EVENT_STATS

    @ray_tpu.remote
    def one():
        return 1

    assert ray_tpu.get(one.remote(), timeout=60) == 1
    core = _global_worker().backend
    # the daemon process serves request_lease etc. — ask IT for its stats
    stats = core.io.run(core.daemon.call("event_stats", timeout=10))
    handlers = stats["handlers"]
    assert handlers.get("request_lease", {}).get("count", 0) >= 1, handlers
    assert any(l["name"] for l in stats["loops"])
    # driver-side: this process's own RpcServer dispatches (owner services
    # like get_object_status) record into the module-global registry; at
    # minimum the registry exists and renders without error
    from ray_tpu.observability.metrics import render

    GLOBAL_EVENT_STATS.ensure_metrics()
    text = render()
    assert "raytpu_event_loop_lag_seconds" in text


# ---------------------------------------------------------------------------
# deadline propagation


def test_deadline_scope_truncates_direct_get(ray_start_regular):
    @ray_tpu.remote
    def slow():
        time.sleep(30)
        return 1

    ref = slow.remote()
    t0 = time.monotonic()
    with deadline_scope(2.0):
        with pytest.raises(ray_tpu.GetTimeoutError):
            ray_tpu.get(ref, timeout=None)  # None defers to the budget
    assert time.monotonic() - t0 < 20
    ray_tpu.cancel(ref, force=True)


def test_deadline_propagates_into_nested_task_get(ray_start_regular):
    """The acceptance case: a nested get() INSIDE a remote task inherits
    the submitter's remaining budget instead of waiting forever."""

    @ray_tpu.remote(num_cpus=1)
    def slow():
        time.sleep(60)
        return 1

    @ray_tpu.remote(num_cpus=1)
    def nested():
        from ray_tpu.core.exceptions import GetTimeoutError

        inner = slow.remote()
        try:
            ray_tpu.get(inner, timeout=None)
            return "no-timeout"
        except GetTimeoutError:
            return "truncated"
        finally:
            ray_tpu.cancel(inner, force=True)

    with deadline_scope(3.0):
        ref = nested.remote()  # spec carries ~3s of remaining budget
    t0 = time.monotonic()
    assert ray_tpu.get(ref, timeout=90) == "truncated"
    assert time.monotonic() - t0 < 45  # not the inner task's 60s


def test_effective_timeout_combines_budgets():
    assert effective_timeout(7.5) == 7.5  # no ambient deadline
    assert effective_timeout(None) is None
    with deadline_scope(1.0):
        assert effective_timeout(None) <= 1.0
        assert effective_timeout(0.2) <= 0.2
        with deadline_scope(50.0):  # nested scopes never extend
            assert effective_timeout(None) <= 1.0
    d = Deadline.after(0.0)
    assert d.expired


# ---------------------------------------------------------------------------
# escalating reaping


def test_reaper_kills_sigterm_ignoring_child():
    proc = subprocess.Popen(
        [
            sys.executable,
            "-c",
            "import signal, sys, time\n"
            "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
            "print('armored', flush=True)\n"
            "time.sleep(600)\n",
        ],
        stdout=subprocess.PIPE,
    )
    assert proc.stdout.readline().strip() == b"armored"
    # plain SIGTERM alone would hang forever; the escalating reap must not
    t0 = time.monotonic()
    assert reap_process(proc, term_grace_s=0.5, kill_grace_s=5.0)
    assert time.monotonic() - t0 < 10
    assert proc.poll() is not None


def test_reaper_is_noop_on_dead_process():
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait(timeout=30)
    assert reap_process(proc)  # already gone: True, instantly


def test_chaos_killed_node_leaves_no_pids(shutdown_only):
    """Acceptance: a hard-killed (chaos) node plus full teardown leaves
    zero worker_main/node_main processes for this cluster."""
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(num_cpus=1)
    controller_addr = f"127.0.0.1:{cluster.controller_port}"
    try:
        ray_tpu.init(address=cluster.address)
        node = cluster.add_node(num_cpus=2)

        @ray_tpu.remote(num_cpus=2)
        def where():
            return os.getpid()

        # lands on the added node (head has 1 CPU); spawns a real worker
        assert ray_tpu.get(where.remote(), timeout=120) > 0
        assert find_runtime_pids(controller_addr=controller_addr)
        cluster.remove_node(node)  # SIGKILL the whole node group
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
    deadline = time.monotonic() + 15
    leaked = find_runtime_pids(controller_addr=controller_addr)
    while leaked and time.monotonic() < deadline:
        time.sleep(0.25)
        leaked = find_runtime_pids(controller_addr=controller_addr)
    assert not leaked, f"leaked runtime pids: {leaked}"


def test_worker_ignoring_sigterm_cannot_survive_daemon_stop(
    shutdown_only, tmp_path
):
    """A worker unresponsive to SIGTERM (here: SIGSTOPped, the closest
    simulation of wedged-in-native-code) is SIGKILLed by the daemon's
    escalating shutdown reap."""
    old = GLOBAL_CONFIG.reap_term_grace_s
    GLOBAL_CONFIG.reap_term_grace_s = 0.5
    pid_file = tmp_path / "frozen_pid"
    try:
        ray_tpu.init(num_cpus=2)

        @ray_tpu.remote
        def freeze(path):
            import signal as _signal

            with open(path, "w") as f:
                f.write(str(os.getpid()))
            os.kill(os.getpid(), _signal.SIGSTOP)  # never returns normally

        freeze.remote(str(pid_file))  # no get: the task never completes
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and not pid_file.exists():
            time.sleep(0.1)
        wpid = int(pid_file.read_text())
        assert wpid > 0
    finally:
        ray_tpu.shutdown()
        GLOBAL_CONFIG.reap_term_grace_s = old
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        try:
            os.kill(wpid, 0)
        except ProcessLookupError:
            break
        time.sleep(0.25)
    with pytest.raises(ProcessLookupError):
        os.kill(wpid, 0)


# ---------------------------------------------------------------------------
# leak-guard machinery sanity


def test_find_runtime_pids_scopes_by_controller_addr():
    # nothing initialized: a bogus controller addr matches nothing
    assert find_runtime_pids(controller_addr="127.0.0.1:1") == []


def test_driver_death_reaps_cluster(tmp_path):
    """A driver killed WITHOUT running shutdown (SIGKILL — the wedged/
    killed-pytest case) must not orphan its head_main/node_main/workers:
    the driver-orphan watch exits them (round-5 'orphaned head_main')."""
    script = tmp_path / "driver.py"
    script.write_text(
        "import time\n"
        "import ray_tpu\n"
        "ray_tpu.init(num_cpus=1)\n"
        "\n"
        "@ray_tpu.remote\n"
        "def ping():\n"
        "    return 1\n"
        "\n"
        "assert ray_tpu.get(ping.remote(), timeout=180) == 1  # spawns a worker\n"
        "print('cluster-up', flush=True)\n"
        "time.sleep(600)\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
    before = set(find_runtime_pids())
    driver = subprocess.Popen(
        [sys.executable, str(script)], stdout=subprocess.PIPE, env=env
    )
    cluster_pids = set()
    try:
        assert driver.stdout.readline().strip() == b"cluster-up", "driver boot failed"
        cluster_pids = set(find_runtime_pids()) - before  # head + its worker(s)
        assert cluster_pids, "no cluster processes appeared?"
        driver.kill()  # SIGKILL: no shutdown, no atexit, nothing
        driver.wait(timeout=30)
        # 1s ppid poll + graceful stop window (hard-exit backstop at 10s)
        deadline = time.monotonic() + 45
        leaked = {p for p in cluster_pids if pid_alive(p)}
        while leaked and time.monotonic() < deadline:
            time.sleep(0.5)
            leaked = {p for p in leaked if pid_alive(p)}
        assert not leaked, f"cluster outlived its dead driver: {leaked}"
    finally:
        if driver.poll() is None:
            driver.kill()
            driver.wait(timeout=10)
        if cluster_pids:
            from ray_tpu.util.reaper import reap_all

            reap_all([p for p in cluster_pids if pid_alive(p)])
