from ray_tpu.core.ids import ActorID, JobID, ObjectID, TaskID


def test_id_sizes_and_lineage_embedding():
    job = JobID.from_index(7)
    actor = ActorID.of(job)
    task = TaskID.for_task(actor)
    obj = ObjectID.from_index(task, 3)

    assert len(job.binary()) == 4
    assert len(actor.binary()) == 12
    assert len(task.binary()) == 20
    assert len(obj.binary()) == 24

    # lineage: each larger id embeds the smaller
    assert actor.job_id() == job
    assert task.actor_id() == actor
    assert obj.task_id() == task
    assert obj.index() == 3
    assert obj.job_id() == job


def test_put_ids_do_not_collide_with_returns():
    job = JobID.from_random()
    task = TaskID.for_driver(job)
    ret = ObjectID.from_index(task, 1)
    put = ObjectID.for_put(task, 1)
    assert ret != put
    assert put.is_put() and not ret.is_put()


def test_id_equality_hash_pickle():
    import pickle

    a = TaskID.for_task(ActorID.of(JobID.from_index(1)))
    b = TaskID(a.binary())
    assert a == b and hash(a) == hash(b)
    assert pickle.loads(pickle.dumps(a)) == a
    assert a != TaskID.for_task(ActorID.of(JobID.from_index(1)))


def test_nil():
    assert JobID.nil().is_nil()
    assert not JobID.from_index(1).is_nil()
