"""Inference engine tests: paged KV cache, continuous batching, engine
edge cases (ISSUE 4). Everything here is CPU-runnable and cluster-free —
the engine is plain in-process machinery; serve integration is covered
in test_serve_llm.py."""

import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.inference.engine import (  # noqa: E402
    EngineConfig,
    EngineDrainingError,
    InferenceEngine,
    RequestFailedError,
)
from ray_tpu.inference.kv_cache import PagedBlockManager  # noqa: E402
from ray_tpu.inference.model_runner import PagedModelRunner  # noqa: E402
from ray_tpu.inference.scheduler import (  # noqa: E402
    FAILED,
    QUEUED,
    ContinuousBatchingScheduler,
    Request,
)
from ray_tpu.models.llama import LlamaConfig, forward, init_params  # noqa: E402


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.tiny()


@pytest.fixture(scope="module")
def params(cfg):
    return init_params(cfg, jax.random.PRNGKey(0))


_dense_fwd = {}


def _dense_greedy(cfg, params, prompt, n):
    # fixed-shape jitted reference: pad to max_seq_len so every step hits
    # ONE compiled program (an unjitted growing-length loop dominates the
    # module's wall time on CPU); causal masking makes the padding inert
    fwd = _dense_fwd.get(cfg)
    if fwd is None:
        fwd = _dense_fwd[cfg] = jax.jit(
            lambda p, t: forward(cfg, p, t)
        )
    toks = list(prompt)
    out = []
    for _ in range(n):
        padded = np.zeros((1, cfg.max_seq_len), np.int32)
        padded[0, : len(toks)] = toks
        logits = fwd(params, padded)
        nxt = int(jnp.argmax(logits[0, len(toks) - 1]))
        out.append(nxt)
        toks.append(nxt)
    return out


# ---------------------------------------------------------------------------
# host-side accounting (no jax compute)


def test_block_manager_alloc_free_evict():
    mgr = PagedBlockManager(num_blocks=8, block_size=4)
    assert mgr.usable_blocks == 7  # block 0 reserved
    assert mgr.grow_to("a", 9)  # 3 blocks
    assert mgr.used_blocks == 3
    assert 0 not in mgr.owned("a")  # null block never handed out
    # all-or-nothing: 5 more blocks don't fit 4 free
    assert not mgr.grow_to("b", 20)
    assert mgr.owned("b") == []
    assert mgr.grow_to("b", 16)  # 4 blocks: exactly fits
    assert mgr.free_blocks == 0
    row = mgr.table_row("a", 6)
    assert len(row) == 6 and row[3:] == [0, 0, 0]
    assert mgr.evict("a") == 3
    assert mgr.total_evictions == 1
    assert mgr.free_blocks == 3
    assert mgr.free("b") == 4
    assert mgr.stats()["utilization"] == 0.0


def test_scheduler_admission_queues_then_admits():
    mgr = PagedBlockManager(num_blocks=5, block_size=4)  # 4 usable
    sched = ContinuousBatchingScheduler(mgr, max_decode_batch=4)
    a = Request("a", prompt=list(range(1, 12)))  # needs 3 blocks (12 tokens)
    b = Request("b", prompt=list(range(1, 8)))  # needs 2 blocks
    sched.add(a)
    sched.add(b)
    plan = sched.schedule()
    # a admitted; b queued behind the exhausted pool (1 block free < 2)
    assert [r.request_id for r in sched.running] == ["a"]
    assert sched.queue_depth() == 1
    assert plan.prefills and plan.prefills[0][0] is a
    sched.finish(a)  # a's blocks return to the pool
    sched.schedule()
    assert [r.request_id for r in sched.running] == ["b"]
    assert sched.queue_depth() == 0
    assert sched.total_admitted == 2


def test_scheduler_preempts_lowest_priority_for_block_growth():
    mgr = PagedBlockManager(num_blocks=6, block_size=4)  # 5 usable
    sched = ContinuousBatchingScheduler(mgr, max_decode_batch=4)
    lo = Request("lo", prompt=list(range(1, 8)), priority=0)  # 2 blocks
    hi = Request("hi", prompt=list(range(1, 8)), priority=1)  # 2 blocks
    sched.add(lo)
    sched.add(hi)
    sched.schedule()
    assert len(sched.running) == 2 and mgr.free_blocks == 1
    # both decode-ready with 8 cached tokens; growing past 2 blocks
    for r in (lo, hi):
        r.prefill_pos = len(r.prompt)
        r.generated.extend([5] * 4)  # context 11 -> needs 3 blocks
    plan = sched.schedule()
    # hi grew into the free block; lo's growth preempted... nobody —
    # lo is the only candidate lower than itself, so ordering matters:
    # hi (priority 1) schedules first, takes the free block; lo then
    # needs one more and evicts... only hi is left, which outranks it —
    # lo stalls instead of preempting higher-priority work.
    assert hi in plan.decodes
    assert lo not in plan.decodes
    assert lo in sched.running  # stalled, not evicted
    # now the roles reverse: drop hi's priority below lo's and grow again
    hi.priority = -1
    lo.generated.extend([5] * 1)
    plan = sched.schedule()
    assert lo in plan.decodes
    assert hi.state == QUEUED and hi.preemptions == 1
    assert sched.waiting[0] is hi  # readmission from the queue FRONT
    assert mgr.total_evictions == 1


# ---------------------------------------------------------------------------
# paged forward correctness


def test_paged_prefill_decode_matches_dense(cfg, params):
    runner = PagedModelRunner(
        cfg, params, num_blocks=32, block_size=8,
        prefill_buckets=(4, 8), decode_buckets=(1, 4),
    )
    mgr = PagedBlockManager(32, 8)
    rs = np.random.RandomState(7)
    state = {}
    for rid, n in (("r0", 11), ("r1", 5), ("r2", 9)):
        prompt = [int(x) for x in rs.randint(1, cfg.vocab_size, size=n)]
        mgr.grow_to(rid, n + 1)
        row = mgr.table_row(rid, runner.max_blocks_per_seq)
        pos = 0
        while pos < n:  # chunked prefill, chunks of <= 4
            chunk = prompt[pos : pos + 4]
            logits = runner.prefill_chunk(chunk, row, pos)
            pos += len(chunk)
        state[rid] = {"prompt": prompt, "gen": [int(logits.argmax())]}
    for _ in range(5):  # batched decode across all three requests
        rids = list(state)
        toks, poss, rows, cls = [], [], [], []
        for rid in rids:
            st = state[rid]
            p = len(st["prompt"]) + len(st["gen"]) - 1
            mgr.grow_to(rid, p + 2)
            toks.append(st["gen"][-1])
            poss.append(p)
            rows.append(mgr.table_row(rid, runner.max_blocks_per_seq))
            cls.append(p + 1)
        logits = runner.decode(toks, poss, rows, cls)
        for rid, lg in zip(rids, logits):
            state[rid]["gen"].append(int(lg.argmax()))
    for st in state.values():
        assert st["gen"] == _dense_greedy(cfg, params, st["prompt"], 6)


# ---------------------------------------------------------------------------
# engine edge cases


@pytest.fixture(scope="module")
def engine(cfg, params):
    ec = EngineConfig(
        num_blocks=64, block_size=8, prefill_buckets=(8, 16),
        decode_buckets=(1, 2, 4, 8), max_decode_batch=8,
        max_new_tokens_default=8,
    )
    eng = InferenceEngine(cfg, params, ec).start()
    yield eng
    eng.stop()


def test_engine_concurrent_streams_match_dense_zero_recompiles(cfg, params, engine):
    rs = np.random.RandomState(3)
    prompts = [
        [int(x) for x in rs.randint(1, cfg.vocab_size, size=n)]
        for n in (5, 9, 12, 4, 7, 6)
    ]
    results = {}

    def consume(i):
        results[i] = list(engine.generate(prompts[i], max_new_tokens=6))

    threads = [threading.Thread(target=consume, args=(i,)) for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    for i, p in enumerate(prompts):
        assert results[i] == _dense_greedy(cfg, params, p, 6), f"prompt {i}"
    # fixed-shape buckets: warmup compiled one program per bucket and
    # serving added NOTHING
    assert engine.runner.recompiles_after_warmup() == 0
    # prefill + decode buckets + the COW block-copy program
    assert engine.runner.compile_count() == 2 + 4 + 1
    # all blocks returned
    assert engine.blocks.used_blocks == 0


def test_engine_temperature_sampling_reproducible(cfg, engine):
    prompt = [3, 1, 4, 1, 5]
    a = list(engine.generate(prompt, max_new_tokens=6, temperature=0.8, seed=42))
    b = list(engine.generate(prompt, max_new_tokens=6, temperature=0.8, seed=42))
    assert a == b
    assert len(a) == 6


def test_engine_block_exhaustion_queues_then_admits(cfg, params):
    # pool fits ONE max-length sequence (plus null): the second request
    # must wait in the admission queue until the first finishes
    ec = EngineConfig(
        num_blocks=9, block_size=8, prefill_buckets=(16,),
        decode_buckets=(1, 2), max_decode_batch=2, max_new_tokens_default=8,
    )
    eng = InferenceEngine(cfg, params, ec).start()
    try:
        p1 = [1, 2, 3] * 5  # 15 tokens -> 2 blocks, grows while decoding
        p2 = [4, 5, 6] * 5
        r1 = eng.submit(p1, max_new_tokens=30)  # ends holding 6 blocks
        # give r1's prefill a head start so it holds the pool
        deadline = time.monotonic() + 10
        while eng.blocks.used_blocks == 0 and time.monotonic() < deadline:
            time.sleep(0.002)
        r2 = eng.submit(p2, max_new_tokens=30)
        saw_queued = False
        for _ in range(1000):
            if eng.scheduler.queue_depth() > 0:
                saw_queued = True
                break
            time.sleep(0.001)
        out1 = list(eng.tokens(r1, timeout=30))
        out2 = list(eng.tokens(r2, timeout=30))
        assert saw_queued, "second request never waited for blocks"
        assert out1 == _dense_greedy(cfg, params, p1, 30)
        assert out2 == _dense_greedy(cfg, params, p2, 30)
        assert eng.scheduler.total_admitted == 2
        assert eng.blocks.used_blocks == 0
    finally:
        eng.stop()


def test_engine_mid_decode_cancellation_frees_blocks(cfg, params):
    ec = EngineConfig(
        num_blocks=32, block_size=8, prefill_buckets=(16,),
        decode_buckets=(1,), max_decode_batch=1,
    )
    eng = InferenceEngine(cfg, params, ec).start()
    try:
        rid = eng.submit([1, 2, 3, 4, 5], max_new_tokens=500)
        it = eng.tokens(rid, timeout=30)
        first = [next(it), next(it)]  # stream is live mid-decode
        assert len(first) == 2
        assert eng.blocks.used_blocks > 0
        assert eng.cancel(rid)
        # stream terminates (cancel surfaces as clean end-of-stream)
        rest = list(it)
        assert len(rest) < 500
        deadline = time.monotonic() + 10
        while eng.blocks.used_blocks and time.monotonic() < deadline:
            time.sleep(0.002)
        assert eng.blocks.used_blocks == 0
        assert not eng.scheduler.has_work()
    finally:
        eng.stop()


def test_engine_preemption_readmission_matches_dense(cfg, params):
    # pool too small for two grown sequences: the lower-priority request
    # gets evicted mid-decode and must re-prefill prompt+generated on
    # readmission — its final stream must still match dense greedy.
    ec = EngineConfig(
        num_blocks=11, block_size=8, prefill_buckets=(16, 32),
        decode_buckets=(1, 2), max_decode_batch=2, max_new_tokens_default=40,
    )
    eng = InferenceEngine(cfg, params, ec).start()
    try:
        lo_p = [1, 2, 3, 4, 5, 6, 7] * 2  # 14 tokens
        hi_p = [8, 9, 10, 11, 12, 13] * 2  # 12 tokens
        lo = eng.submit(lo_p, max_new_tokens=40, priority=0)
        hi = eng.submit(hi_p, max_new_tokens=40, priority=1)
        out_lo = list(eng.tokens(lo, timeout=60))
        out_hi = list(eng.tokens(hi, timeout=60))
        assert out_hi == _dense_greedy(cfg, params, hi_p, 40)
        assert out_lo == _dense_greedy(cfg, params, lo_p, 40)
        assert eng.blocks.used_blocks == 0
    finally:
        eng.stop()


def test_engine_drain_finishes_in_flight_rejects_new(cfg, params):
    ec = EngineConfig(
        num_blocks=64, block_size=8, prefill_buckets=(16,),
        decode_buckets=(1, 2, 4), max_decode_batch=4,
    )
    eng = InferenceEngine(cfg, params, ec).start()
    try:
        rids = [eng.submit([1 + i, 2, 3], max_new_tokens=30) for i in range(3)]
        eng.begin_drain(grace_s=30)
        with pytest.raises(EngineDrainingError):
            eng.submit([9, 9, 9])
        # every in-flight stream completes cleanly inside the grace
        for i, rid in enumerate(rids):
            out = list(eng.tokens(rid, timeout=30))
            assert out == _dense_greedy(cfg, params, [1 + i, 2, 3], 30)
        assert eng.wait_idle(timeout=10)
    finally:
        eng.stop()


def test_engine_drain_grace_expiry_fails_stragglers(cfg, params):
    ec = EngineConfig(
        num_blocks=64, block_size=8, prefill_buckets=(16,),
        decode_buckets=(1,), max_decode_batch=1,
    )
    eng = InferenceEngine(cfg, params, ec)  # NOT started: nothing decodes
    try:
        rid = eng.submit([1, 2, 3], max_new_tokens=5)
        eng.begin_drain(grace_s=0.0)  # grace already over
        eng.start()
        with pytest.raises(RequestFailedError):
            list(eng.tokens(rid, timeout=30))
    finally:
        eng.stop()


def test_engine_expired_deadline_fails_request(cfg, params):
    ec = EngineConfig(
        num_blocks=32, block_size=8, prefill_buckets=(16,), decode_buckets=(1,),
        max_decode_batch=1,
    )
    eng = InferenceEngine(cfg, params, ec).start()
    try:
        rid = eng.submit([1, 2, 3], max_new_tokens=5, timeout_s=0.0)
        with pytest.raises(RequestFailedError):
            list(eng.tokens(rid, timeout=30))
        assert eng.blocks.used_blocks == 0
    finally:
        eng.stop()


def test_engine_rejects_batch_beyond_buckets(cfg, params):
    """A decode batch cap the compiled bucket set can't cover must fail
    at init, not as a repeated runtime fail-all inside step()."""
    with pytest.raises(ValueError, match="decode bucket"):
        InferenceEngine(
            cfg,
            params,
            EngineConfig(
                num_blocks=32, block_size=8, prefill_buckets=(16,),
                decode_buckets=(1, 2), max_decode_batch=4,
            ),
        )


def test_tokens_timeout_keeps_stream_resumable(cfg, params):
    """An inter-token timeout raises TimeoutError but must NOT tear down
    the stream: the request keeps running and a retry resumes (a popped
    queue would silently drop every later token and KeyError the retry)."""
    ec = EngineConfig(
        num_blocks=32, block_size=8, prefill_buckets=(16,), decode_buckets=(1,),
        max_decode_batch=1,
    )
    eng = InferenceEngine(cfg, params, ec)  # NOT started: no tokens flow yet
    try:
        rid = eng.submit([1, 2, 3], max_new_tokens=4)
        with pytest.raises(TimeoutError):
            next(eng.tokens(rid, timeout=0.05))
        eng.start()
        assert len(list(eng.tokens(rid, timeout=30))) == 4
        assert eng.blocks.used_blocks == 0
    finally:
        eng.stop()


def test_expired_request_behind_stuck_head_is_reaped():
    """Deadline expiry must sweep the WHOLE admission queue, not just the
    head: an expired request parked behind a non-admittable head fails
    promptly instead of hanging its caller until the head admits."""

    class _Expired:
        expired = True

    mgr = PagedBlockManager(4, 4)  # 3 usable blocks
    sched = ContinuousBatchingScheduler(mgr)
    head = Request(request_id="head", prompt=list(range(40)))  # needs 11 blocks: stuck
    behind = Request(request_id="behind", prompt=[1, 2], deadline=_Expired())
    sched.add(head)
    sched.add(behind)
    plan = sched.schedule()
    assert behind in plan.reaped and behind.state == FAILED
    assert head.state == QUEUED and sched.queue_depth() == 1


def test_abandoned_finished_stream_is_reaped(cfg, params):
    """A caller that submits and never drains (gave up without cancel())
    must not pin its token queue in the replica forever — the engine reaps
    finished-but-undrained streams after finished_stream_ttl_s."""
    ec = EngineConfig(
        num_blocks=32, block_size=8, prefill_buckets=(16,), decode_buckets=(1,),
        max_decode_batch=1, finished_stream_ttl_s=0.2,
    )
    eng = InferenceEngine(cfg, params, ec).start()
    try:
        rid = eng.submit([1, 2, 3], max_new_tokens=3)
        deadline = time.monotonic() + 10
        while rid in eng._out and time.monotonic() < deadline:
            time.sleep(0.05)
        assert rid not in eng._out and rid not in eng._finished_at
        with pytest.raises(KeyError):
            next(eng.tokens(rid))
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# prefix caching (ISSUE 7): radix reuse, COW, refcount accounting


def test_prefix_cache_manager_hit_lru_and_refcounts():
    """Host-side radix-index mechanics: full blocks registered, hit,
    shared refcounted, revived off the LRU, and reclaimed under pool
    pressure — no jax involved."""
    mgr = PagedBlockManager(8, 4, prefix_cache_enabled=True)  # 7 usable
    toks = list(range(10, 22))  # 12 tokens = 3 full blocks
    assert mgr.grow_to("a", 12)
    assert mgr.register_prefix("a", toks) == 3
    assert mgr.free("a") == 3
    # unreferenced cached blocks count as FREE capacity (reclaimable),
    # but stay indexed until pressure needs them
    assert mgr.used_blocks == 0 and mgr.cached_blocks == 3
    # partial-prefix hit: 2 of 3 blocks match, third diverges
    cached, cow = mgr.acquire_prefix("b", toks[:8] + [99, 98, 97, 96])
    assert cached == 8 and cow == []
    shared = mgr.owned("b")
    assert len(shared) == 2 and all(mgr.refcount(x) == 1 for x in shared)
    assert mgr.grow_to("b", 13)  # tail blocks from free/LRU
    # pool pressure reclaims the remaining unreferenced cached block
    # (b holds 4: 2 shared + 2 private; c's 3 drain free list + LRU)
    assert mgr.grow_to("c", 4 * (7 - 3 - 1))
    assert mgr.free_blocks == 0
    stats = mgr.prefix_stats()
    assert stats["indexed_blocks"] < 3  # LRU eviction dropped index entries
    mgr.free("b")
    mgr.free("c")
    assert mgr.used_blocks == 0


def test_prefix_cache_cow_under_preemption_accounting():
    """COW + sharer eviction accounting: evicting one sharer leaves the
    other's blocks intact (refcount decrement, not a free), readmission
    re-acquires from the cache, and after everything finishes the free /
    cached / refcount books balance exactly."""
    mgr = PagedBlockManager(8, 4, prefix_cache_enabled=True)  # 7 usable
    p = list(range(30, 38))  # 8 tokens = 2 full blocks
    # A: admit, prefill, register its prompt blocks
    assert mgr.grow_to("A", 9)  # 3 blocks
    assert mgr.register_prefix("A", p) == 2
    a_blocks = mgr.owned("A")
    # B shares A's prompt blocks (prefix hit) + 1 private tail block
    cached, cow = mgr.acquire_prefix("B", p + [50, 51])
    assert cached == 8 and cow == []
    assert mgr.owned("B")[:2] == a_blocks[:2]
    assert mgr.grow_to("B", 11)
    assert [mgr.refcount(x) for x in a_blocks[:2]] == [2, 2]
    used_with_sharing = mgr.used_blocks
    assert used_with_sharing == 4  # 3 (A) + 1 private tail (B)
    # evict the sharer (preemption): shared blocks survive for A,
    # B's private tail returns to the pool
    assert mgr.evict("B") == 3
    assert mgr.total_evictions == 1
    assert [mgr.refcount(x) for x in a_blocks[:2]] == [1, 1]
    assert mgr.used_blocks == 3 and mgr.owned("A") == a_blocks
    # readmission hits the cache again — near-free re-prefill
    cached, _ = mgr.acquire_prefix("B", p + [50, 51])
    assert cached == 8
    assert mgr.grow_to("B", 11)
    # finish both: refcounts drain to zero, registered blocks park on
    # the LRU (still free capacity), private blocks go straight back
    mgr.free("B")
    mgr.free("A")
    assert mgr.used_blocks == 0
    assert mgr.free_blocks == 7
    assert mgr.cached_blocks == 2
    assert all(mgr.refcount(x) == 0 for x in range(1, 8))
    # full-prompt hit takes the COW path: last shared block duplicated
    cached, cow = mgr.acquire_prefix("C", p)
    assert cached == len(p) - 1  # one token recomputes into the copy
    assert len(cow) == 1
    src, dst = cow[0]
    assert mgr.owned("C")[-1] == dst and mgr.refcount(src) == 1  # pinned
    mgr.cow_copied("C")
    assert mgr.refcount(src) == 0  # pin released, back to the cache
    assert mgr.cow_copies_total == 1
    mgr.free("C")
    assert mgr.used_blocks == 0 and mgr.free_blocks == 7


def test_engine_shared_prefix_matches_dense_with_zero_recompiles(cfg, params, engine):
    """Two requests sharing a system prompt: the second's prefill skips
    the cached blocks yet streams IDENTICAL tokens to the uncached dense
    reference, with zero post-warmup recompiles; an exact full-prompt
    repeat exercises the COW path and also matches."""
    ps0 = engine.blocks.prefix_stats()
    sys_prompt = [91, 17, 53, 28, 64, 39, 75, 46] * 2  # 16 tokens = 2 blocks
    tails = ([101, 7], [55, 9])
    outs = [
        list(engine.generate(sys_prompt + t, max_new_tokens=6)) for t in tails
    ]
    for t, out in zip(tails, outs):
        assert out == _dense_greedy(cfg, params, sys_prompt + t, 6)
    # exact repeat of a FULL prompt: every block hits -> COW + 1-token tail
    rep1 = list(engine.generate(sys_prompt, max_new_tokens=6))
    rep2 = list(engine.generate(sys_prompt, max_new_tokens=6))
    assert rep1 == rep2 == _dense_greedy(cfg, params, sys_prompt, 6)
    ps1 = engine.blocks.prefix_stats()
    assert ps1["hits_total"] - ps0["hits_total"] >= 2  # warm tail + repeat
    assert ps1["tokens_saved_total"] - ps0["tokens_saved_total"] >= 16 + 15
    assert ps1["cow_copies_total"] - ps0["cow_copies_total"] >= 1
    assert engine.runner.recompiles_after_warmup() == 0
    assert engine.blocks.used_blocks == 0  # every request's refs released
