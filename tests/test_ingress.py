"""ISSUE 12: overload-safe multi-tenant ingress — the HTTP/SSE front
door with per-tenant fairness, shed-before-queue, and graceful
degradation.

Layers under test:

* policy units — cost-denominated :class:`TokenBucket` (deterministic
  via injected clocks), the :func:`shed_verdict` priority ladder, and
  the tenant→replica rendezvous hash;
* client-disconnect propagation — an HTTP client that goes away
  mid-stream must reach ``engine.cancel()``: KV blocks freed, the
  request counted cancelled, ``total_admitted`` NOT re-counted
  (pre-PR the producer decoded the whole stream for nobody);
* shed == never-admitted — the ingress shed count and the engine's
  ``total_admitted`` reconcile EXACTLY: a 429 provably consumed zero
  engine queue slots;
* router hardening — a gossip-capable deployment whose signals all went
  stale falls back with ``policy="stale_fallback"``, split from the
  plain pow-2 label;
* the many-tenant chaos E2E — heavy-tailed tenants + one abusive tenant
  + a seeded mid-run replica kill: the abusive tenant is shed (429s),
  well-behaved tenants see ZERO client-visible errors and byte-exact
  greedy streams (the PR 10 resumable path makes the kill invisible
  through HTTP), and the run reproduces from the logged chaos env line
  alone.
"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.ingress import (
    CLASS_PRIORITY,
    IngressConfig,
    IngressShedError,
    TenantPolicy,
    TokenBucket,
    http_stream,
    pick_ingress,
    shed_verdict,
)

pytest.importorskip("jax")

import jax  # noqa: E402

from ray_tpu.inference.engine import EngineConfig, InferenceEngine  # noqa: E402
from ray_tpu.models.llama import LlamaConfig, init_params  # noqa: E402


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.tiny()


@pytest.fixture(scope="module")
def params(cfg):
    return init_params(cfg, jax.random.PRNGKey(0))


_EC = dict(
    num_blocks=64, block_size=8, prefill_buckets=(8, 32),
    decode_buckets=(1, 8), max_decode_batch=8, max_new_tokens_default=8,
)


# ---------------------------------------------------------------------------
# policy units (no cluster, no jax needed beyond the import gate)


def test_token_bucket_refill_and_retry_after():
    b = TokenBucket(rate=10.0, burst=100.0)
    t0 = b.stamp
    assert b.try_take(60, now=t0) == 0.0          # burst covers it
    assert b.try_take(60, now=t0) > 0.0           # 40 left: refused
    assert b.level == pytest.approx(40.0)         # refusal takes nothing
    # the quoted wait is exact: need 20 more units at 10/s = 2s
    assert b.try_take(60, now=t0) == pytest.approx(2.0)
    assert b.try_take(60, now=t0 + 2.0) == 0.0    # honest Retry-After
    # a single request above the whole burst is quoted against the cap
    # (servable, just slowly), then drives the bucket negative
    big = TokenBucket(rate=10.0, burst=50.0)
    t = big.stamp
    assert big.try_take(500, now=t) == 0.0
    assert big.level == pytest.approx(-450.0)
    wait = big.try_take(500, now=t)
    assert wait == pytest.approx(50.0)            # refill a FULL bucket


def test_shed_verdict_priority_ladder():
    cfg = IngressConfig(
        shed_outstanding_per_replica=100.0, shed_queue_fraction=0.5
    )
    # no fresh gossip → never shed blind
    assert shed_verdict({"reporting": 0, "outstanding_tokens": 9e9}, 0, cfg) is None
    # load ladder: batch sheds at >1x, standard >2x, interactive >3x
    p = {"reporting": 2, "outstanding_tokens": 300.0, "queue_depth": 0,
         "max_queue_depth": 256}
    assert shed_verdict(p, CLASS_PRIORITY["batch"], cfg) == "load"
    assert shed_verdict(p, CLASS_PRIORITY["standard"], cfg) is None
    p2 = dict(p, outstanding_tokens=500.0)
    assert shed_verdict(p2, CLASS_PRIORITY["standard"], cfg) == "load"
    assert shed_verdict(p2, CLASS_PRIORITY["interactive"], cfg) is None
    assert shed_verdict(dict(p, outstanding_tokens=700.0),
                        CLASS_PRIORITY["interactive"], cfg) == "load"
    # queue watermark: below-top classes shed at the fraction, everyone
    # sheds once the queues are actually full
    q = {"reporting": 2, "outstanding_tokens": 0.0, "queue_depth": 128,
         "max_queue_depth": 256}
    assert shed_verdict(q, CLASS_PRIORITY["standard"], cfg) == "queue_pressure"
    assert shed_verdict(q, CLASS_PRIORITY["interactive"], cfg) is None
    qfull = dict(q, queue_depth=256)
    assert shed_verdict(qfull, CLASS_PRIORITY["interactive"], cfg) == "queue_pressure"
    # disabled load watermark
    off = IngressConfig(shed_outstanding_per_replica=0.0)
    assert shed_verdict(p2, 0, off) is None


def test_pick_ingress_rendezvous_stable_and_spread():
    addrs = [f"127.0.0.1:{8000 + i}" for i in range(4)]
    picks = {t: pick_ingress(t, addrs) for t in (f"tenant-{i}" for i in range(64))}
    # deterministic: same tenant -> same door, independent of list order
    for t, a in picks.items():
        assert pick_ingress(t, list(reversed(addrs))) == a
    # population spreads over every door
    assert len(set(picks.values())) == len(addrs)
    # removing a door only moves the tenants that were behind it
    survivors = addrs[1:]
    moved = sum(
        1 for t, a in picks.items() if pick_ingress(t, survivors) != a
    )
    assert moved == sum(1 for a in picks.values() if a == addrs[0])
    with pytest.raises(ValueError):
        pick_ingress("t", [])


# ---------------------------------------------------------------------------
# serve integration: disconnect-cancel + exact shed reconciliation


def _run_llm_and_ingress(cfg, ing_cfg, *, llm_replicas=1, ing_replicas=1,
                         ing_name="ing"):
    dep = serve.llm_deployment(
        cfg, engine=EngineConfig(**_EC), name="llm", num_replicas=llm_replicas,
        route_prefix="/llm", ray_actor_options={"num_cpus": 0.25},
    )
    handle = serve.run(dep.bind())
    serve.run(
        serve.ingress_deployment(
            "llm", ing_cfg, name=ing_name, num_replicas=ing_replicas,
        ).bind(),
        name=ing_name,
    )
    return handle, serve.ingress_addresses(ing_name)


def test_http_ingress_disconnect_shed_and_reconcile(cfg, params):
    """One cluster, three gates: (1) SSE streams are byte-exact vs a
    local reference engine; (2) a client disconnect mid-stream reaches
    engine.cancel() — blocks freed, total_admitted NOT re-counted; (3)
    per-tenant rate shedding reconciles EXACTLY with the engine's
    admission counter (shed == never admitted), and serve.status()
    surfaces the shed/queue pressure."""
    ing_cfg = IngressConfig(
        target="llm",
        tenants={
            "abuser": TenantPolicy(rate=2.0, burst=50.0, tenant_class="batch"),
            "vip": TenantPolicy(tenant_class="interactive"),
        },
    )
    ray_tpu.init(num_cpus=4)
    try:
        handle, addrs = _run_llm_and_ingress(cfg, ing_cfg)
        addr = addrs[0]

        def estats():
            return ray_tpu.get(handle.method("engine_stats")(), timeout=60)

        ref = InferenceEngine(cfg, params, EngineConfig(**_EC)).start()
        try:
            expected = list(ref.generate([3, 7, 11, 5], max_new_tokens=6))
        finally:
            ref.stop()

        # -- 1. greedy SSE roundtrip is byte-exact
        toks = list(http_stream(
            addr, {"prompt": [3, 7, 11, 5], "max_new_tokens": 6}, tenant="vip",
        ))
        assert toks == expected

        # -- 2. client disconnect mid-stream → engine.cancel()
        base = estats()["scheduler"]["total_admitted"]
        gen = http_stream(
            addr, {"prompt": [3, 7, 11], "max_new_tokens": 48}, tenant="vip",
        )
        assert next(gen) is not None and next(gen) is not None
        gen.close()  # the HTTP connection drops here
        deadline = time.monotonic() + 30
        s = None
        while time.monotonic() < deadline:
            s = estats()
            if (
                s["scheduler"]["running"] == 0
                and s["blocks"]["used_blocks"] == 0
                and s["scheduler"]["queue_depth"] == 0
            ):
                break
            time.sleep(0.2)
        assert s["scheduler"]["running"] == 0, s["scheduler"]
        assert s["blocks"]["used_blocks"] == 0, s["blocks"]
        # the cancelled request was admitted ONCE and never re-counted
        assert s["scheduler"]["total_admitted"] == base + 1, s["scheduler"]

        # -- 3. rate-limit shedding reconciles exactly with admission.
        # abuser cost/request = 4 + 8 = 12 against burst 50, refill 2/s:
        # ~4 admitted, the rest shed with an honest Retry-After
        base = estats()["scheduler"]["total_admitted"]
        ok, shed, retry_afters = 0, 0, []
        for _ in range(12):
            try:
                out = list(http_stream(
                    addr, {"prompt": [9, 2, 4, 6], "max_new_tokens": 8},
                    tenant="abuser",
                ))
                assert len(out) == 8
                ok += 1
            except IngressShedError as e:
                assert e.reason == "rate_limit"
                retry_afters.append(e.retry_after)
                shed += 1
        assert ok >= 1 and shed >= 1, (ok, shed)
        assert all(r > 0 for r in retry_afters)
        # EXACT reconcile: every 200 is one admission, every 429 is zero
        assert estats()["scheduler"]["total_admitted"] == base + ok
        # operators see it in serve.status() without scraping /metrics
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            st = serve.status()
            if st["ing"].get("shed_total", 0) >= shed:
                break
            time.sleep(0.25)
        assert st["ing"]["shed_total"] == shed, st["ing"]
        for key in ("queue_depth", "outstanding_tokens", "shed_total"):
            assert key in st["llm"] and key in st["ing"]

        # -- 4. malformed request → 400, counted, never forwarded
        import urllib.error
        import urllib.request
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                f"http://{addr}/generate", data=b'{"nope": 1}',
                headers={"Content-Type": "application/json"},
            ), timeout=30)
        assert ei.value.code == 400

        # -- 5. ISSUE 15 SLO-ledger books over the same traffic: the
        # ingress conservation identity (seen == shed + bad_request +
        # forwarded) and the engine identity (submitted == finished +
        # failed + cancelled + in-flight) both balance EXACTLY through
        # serve.slo_report() — sheds, the disconnect-cancel, and the
        # 400 all landed in exactly one bucket each
        from ray_tpu.observability import slo as _slo

        deadline = time.monotonic() + 20
        while True:
            rep = serve.slo_report()
            books = [b for d in rep["deployments"].values() for b in d["books"]]
            if books and all(b["balanced"] for b in books):
                break
            assert time.monotonic() < deadline, books
            time.sleep(0.5)
        ing_books = [b for b in books if b.get("kind") == "ingress"]
        eng_books = [b for b in books if b.get("kind") == "engine"]
        assert ing_books and eng_books, books
        ib = ing_books[0]
        assert ib["shed"] == shed and ib["bad_request"] == 1, ib
        assert ib["seen"] == ib["shed"] + ib["bad_request"] + ib["forwarded"]
        assert _slo.books_balanced(ib) and _slo.books_balanced(eng_books[0])
        # the aggregated histograms carry the classes the door stamped
        llm = rep["deployments"]["llm"]
        assert llm["ttft_s"]["count"] > 0 and llm["by_class"], llm
        assert "interactive" in llm["by_class"] or "batch" in llm["by_class"]
        # shed requests left flagged ingress flight-recorder entries
        sheds_rec = [
            r for r in rep["flight_recorder"]
            if "shed" in (r.get("flags") or ())
        ]
        assert sheds_rec, rep["flight_recorder"][:5]
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


def test_queue_fraction_shed_spares_interactive(cfg, params):
    """Graceful degradation, deterministically: shed_queue_fraction=0.0
    sheds every below-top class the moment fresh engine gossip exists,
    while interactive traffic still flows — the priority ladder is
    observable end to end through HTTP status codes."""
    ing_cfg = IngressConfig(
        target="llm",
        shed_queue_fraction=0.0,
        tenants={
            "bg": TenantPolicy(tenant_class="batch"),
            "vip": TenantPolicy(tenant_class="interactive"),
        },
    )
    ray_tpu.init(num_cpus=4)
    try:
        _handle, addrs = _run_llm_and_ingress(cfg, ing_cfg, ing_name="ing")
        addr = addrs[0]
        # prime: one vip request starts the ingress router's long-poll;
        # wait until the gossip actually reached it (pressure reporting)
        list(http_stream(addr, {"prompt": [1, 2, 3], "max_new_tokens": 2},
                         tenant="vip"))
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            try:
                list(http_stream(
                    addr, {"prompt": [5, 6], "max_new_tokens": 2}, tenant="bg",
                ))
            except IngressShedError as e:
                assert e.reason == "queue_pressure"
                break
            time.sleep(0.25)
        else:
            pytest.fail("batch tenant was never shed on queue pressure")
        # interactive still flows under the same pressure signal
        out = list(http_stream(
            addr, {"prompt": [1, 2, 3, 4], "max_new_tokens": 4}, tenant="vip",
        ))
        assert len(out) == 4
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# router hardening: stale gossip falls back attributably


def test_router_stale_gossip_counts_stale_fallback():
    """A gossip-capable deployment (no jax needed — any callable with
    routing_stats()) whose signals all age past the TTL must fall back
    to pow-2 under the DISTINCT policy label, so a load test can tell
    'scored path engaged' from 'gossip was stale the whole run'."""
    from ray_tpu.core.config import GLOBAL_CONFIG
    from ray_tpu.observability.rpc_metrics import ROUTER_DECISIONS

    ray_tpu.init(num_cpus=4)
    old_ttl = GLOBAL_CONFIG.serve_routing_stats_ttl_s
    try:
        @serve.deployment(num_replicas=2, ray_actor_options={"num_cpus": 0.1})
        class Gossipy:
            def routing_stats(self):
                return {"outstanding_tokens": 0.0, "queue_depth": 0,
                        "max_queue_depth": 8}

            def __call__(self, x):
                return x

        handle = serve.run(Gossipy.bind(), name="Gossipy")
        ctrl = ray_tpu.get_actor("__serve_controller__")
        ray_tpu.get(
            ctrl.wait_status.remote("Gossipy", min_replicas=2, timeout_s=60),
            timeout=90,
        )
        router = handle._router

        def decisions(policy):
            return ROUTER_DECISIONS._values.get(("Gossipy", policy), 0)

        # wait for fresh gossip → the scored path engages
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            router.choose_replica()
            if decisions("affinity") > 0:
                break
            time.sleep(0.2)
        assert decisions("affinity") > 0, dict(ROUTER_DECISIONS._values)
        # pressure rollup sees both replicas reporting
        p = router.cluster_pressure()
        assert p["reporting"] == 2 and p["max_queue_depth"] == 16, p

        # now every signal is stale by definition: TTL → 0
        GLOBAL_CONFIG.serve_routing_stats_ttl_s = 1e-9
        before_stale = decisions("stale_fallback")
        before_pow2 = decisions("pow2")
        for _ in range(5):
            router.choose_replica()
        assert decisions("stale_fallback") >= before_stale + 5
        assert decisions("pow2") == before_pow2  # split, not lumped
        assert router.cluster_pressure()["reporting"] == 0
    finally:
        GLOBAL_CONFIG.serve_routing_stats_ttl_s = old_ttl
        serve.shutdown()
        ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# the acceptance gate: many tenants + one abuser + seeded replica kill


@pytest.mark.chaos
def test_e2e_many_tenant_chaos_slos_hold(cfg, params):
    """ISSUE 12 gate: heavy-tailed tenants, one abusive tenant
    saturating its bucket, TWO ingress doors over TWO engine replicas,
    and a seeded ReplicaFaultPlan SIGKILLing engines mid-decode. The
    abusive tenant is shed (429 + Retry-After); every well-behaved
    request streams the byte-exact greedy sequence with ZERO
    client-visible errors (the kill is absorbed by the resumable-stream
    tier); shed requests never reached an engine (ingress-side
    conservation); and the whole schedule reproduces from the chaos env
    line the conftest repro helper prints."""
    import os
    import random

    from ray_tpu.util.chaos import ReplicaFaultPlan

    SPEC, SEED = "kill_mid_decode:1.0:25:1", 20260804
    n_tenants, per_tenant, max_new = 4, 5, 6

    # heavy-tailed prompt lengths (bounded Pareto), per-tenant shared
    # system prefix so the affinity scorer has something to pin
    rnd = random.Random(1234)
    prefixes = {
        t: [10 + t] * (8 + 2 * t) for t in range(n_tenants)
    }
    prompts = {}
    for t in range(n_tenants):
        for i in range(per_tenant):
            tail_len = min(24, max(2, int(rnd.paretovariate(1.2))))
            tail = [rnd.randrange(1, 250) for _ in range(tail_len)]
            prompts[(t, i)] = prefixes[t] + tail

    # expected sequences from an undisturbed local engine (greedy →
    # deterministic continuation makes the killed-and-resumed streams
    # byte-exact). Computed BEFORE the env plan is exported: see
    # test_stream_resume for the self-SIGKILL rationale.
    ref = InferenceEngine(cfg, params, EngineConfig(**_EC)).start()
    try:
        expected = {
            k: list(ref.generate(p, max_new_tokens=max_new))
            for k, p in prompts.items()
        }
    finally:
        ref.stop()

    os.environ["RAY_TPU_testing_replica_chaos"] = SPEC
    os.environ["RAY_TPU_testing_replica_chaos_seed"] = str(SEED)
    ray_tpu.init(num_cpus=4)
    try:
        # the conftest repro contract (same as PR 10's tests): a failure
        # here prints ONE env line that replays this exact schedule
        from conftest import _chaos_repro_line

        line = _chaos_repro_line("tests/test_ingress.py::e2e")
        assert line and SPEC in line and str(SEED) in line, line

        ing_cfg = IngressConfig(
            target="llm",
            shed_outstanding_per_replica=2048.0,
            tenants={
                "abuser": TenantPolicy(
                    rate=3.0, burst=40.0, tenant_class="batch"
                ),
                **{
                    f"tenant-{t}": TenantPolicy(tenant_class="interactive")
                    for t in range(n_tenants)
                },
            },
        )
        _handle, addrs = _run_llm_and_ingress(
            cfg, ing_cfg, llm_replicas=2, ing_replicas=2, ing_name="ing",
        )
        ctrl = ray_tpu.get_actor("__serve_controller__")
        ray_tpu.get(
            ctrl.wait_status.remote("llm", min_replicas=2, timeout_s=90),
            timeout=120,
        )

        results, errors, ttfts = {}, {}, []
        shed_count, abuser_ok = [0], [0]
        lock = threading.Lock()

        def tenant_load(t):
            tenant = f"tenant-{t}"
            addr = pick_ingress(tenant, addrs)
            for i in range(per_tenant):
                key = (t, i)
                try:
                    t0 = time.monotonic()
                    first, toks = None, []
                    for tok in http_stream(
                        addr,
                        {"prompt": prompts[key], "max_new_tokens": max_new},
                        tenant=tenant, connect_timeout=150.0,
                    ):
                        if first is None:
                            first = time.monotonic() - t0
                        toks.append(tok)
                    with lock:
                        results[key] = toks
                        ttfts.append(first if first is not None else 0.0)
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors[key] = e

        def abuser_load():
            addr = pick_ingress("abuser", addrs)
            for _ in range(30):
                try:
                    list(http_stream(
                        addr, {"prompt": [7, 7, 7, 7], "max_new_tokens": 8},
                        tenant="abuser", connect_timeout=150.0,
                    ))
                    with lock:
                        abuser_ok[0] += 1
                except IngressShedError as e:
                    assert e.retry_after > 0
                    with lock:
                        shed_count[0] += 1
                time.sleep(0.05)

        threads = [
            threading.Thread(target=tenant_load, args=(t,))
            for t in range(n_tenants)
        ] + [threading.Thread(target=abuser_load)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=150)
        assert not any(th.is_alive() for th in threads), "load never finished"

        # -- SLOs: zero client-visible errors, byte-exact streams
        assert not errors, errors
        bad = {k: (results[k], expected[k]) for k in expected
               if results.get(k) != expected[k]}
        assert not bad, bad
        # bounded TTFT even across the kill (p99 over 20 streams = max)
        assert max(ttfts) < 60.0, sorted(ttfts)[-3:]

        # -- the abuser was actually shed, and sheds never reached an
        # engine: at the door, requests either forwarded or 429'd
        assert shed_count[0] > 0, (shed_count, abuser_ok)
        replicas = ray_tpu.get(ctrl.get_replicas.remote("ing"), timeout=60)
        dbg = [
            ray_tpu.get(
                r.handle_request.remote("debug_stats", [], {}, ""), timeout=60
            )
            for r in replicas
        ]
        total_ok = sum(
            n for d in dbg for k, n in d["outcomes"].items()
            if k.endswith(":ok")
        )
        total_shed = sum(d["shed_total"] for d in dbg)
        forwarded = sum(d["forwarded_total"] for d in dbg)
        n_requests = n_tenants * per_tenant + 30
        assert total_ok + total_shed == n_requests, (dbg, n_requests)
        assert forwarded == n_requests - total_shed, (forwarded, total_shed)
        assert total_ok == n_tenants * per_tenant + abuser_ok[0]

        # -- the kill provably landed mid-run and was absorbed: the
        # ingress routers resumed streams, the controller replaced the
        # dead engine replica(s)
        resumes = sum(d["stream_resumes"].get("llm", 0) for d in dbg)
        assert resumes > 0, dbg
        st = ray_tpu.get(
            ctrl.wait_status.remote("llm", min_replicas=2, timeout_s=120),
            timeout=150,
        )
        assert st["replicas"] == 2 and st["restarts"]["death"] >= 1, st
        # the scored (affinity) path engaged under load at the doors
        affinity = sum(
            d["router_decisions"].get("llm:affinity", 0) for d in dbg
        )
        assert affinity > 0, [d["router_decisions"] for d in dbg]

        # -- reproducibility: the seeded schedule is a pure function of
        # (seed, consult order) — the logged env line replays it
        p1, p2 = ReplicaFaultPlan(SPEC, SEED), ReplicaFaultPlan(SPEC, SEED)
        phases = ["prefill"] * 4 + ["decode"] * 30
        s1 = [p1.consult(p) for p in phases]
        assert s1 == [p2.consult(p) for p in phases]
        assert p1.injections == 1
    finally:
        os.environ.pop("RAY_TPU_testing_replica_chaos", None)
        os.environ.pop("RAY_TPU_testing_replica_chaos_seed", None)
        from ray_tpu.core.config import GLOBAL_CONFIG

        GLOBAL_CONFIG.testing_replica_chaos = ""
        GLOBAL_CONFIG.testing_replica_chaos_seed = 0
        serve.shutdown()
        ray_tpu.shutdown()


def test_bucket_state_survives_ingress_replica_restart(cfg, params):
    """ISSUE 13 satellite: per-tenant token-bucket fill levels are
    snapshot to the serve controller on a timer and restored by a
    replacement replica — killing the door mid-depletion must NOT hand
    the tenant a fresh burst. Pre-persistence, every restart reset every
    tenant's budget (buckets were per-replica memory)."""
    from ray_tpu.core.config import GLOBAL_CONFIG

    # near-zero refill: any admission after the restart can only come
    # from a (wrongly) refilled burst, never from honest refill. Burst
    # covers exactly two requests of cost 4 + 8 = 12.
    ing_cfg = IngressConfig(
        target="llm",
        tenants={"miser": TenantPolicy(rate=0.001, burst=24.0)},
    )
    old_period = GLOBAL_CONFIG.serve_ingress_bucket_snapshot_period_s
    GLOBAL_CONFIG.serve_ingress_bucket_snapshot_period_s = 0.25
    ray_tpu.init(num_cpus=4)
    try:
        _handle, addrs = _run_llm_and_ingress(cfg, ing_cfg, ing_name="ing")
        addr = addrs[0]

        def one(expect_ok: bool, a: str) -> bool:
            try:
                out = list(http_stream(
                    a, {"prompt": [9, 2, 4, 6], "max_new_tokens": 8},
                    tenant="miser", connect_timeout=120.0,
                ))
                assert len(out) == 8
                return True
            except IngressShedError as e:
                assert e.reason == "rate_limit"
                return False

        # deplete the bucket: two admissions, third sheds
        assert one(True, addr) is True
        assert one(True, addr) is True
        assert one(False, addr) is False
        time.sleep(4 * GLOBAL_CONFIG.serve_ingress_bucket_snapshot_period_s)

        # kill the door; the controller replaces it
        ctrl = ray_tpu.get_actor("__serve_controller__")
        victim = ray_tpu.get(ctrl.get_replicas.remote("ing"), timeout=30)[0]
        ray_tpu.kill(victim)
        deadline = time.monotonic() + 90
        new_addr = None
        while time.monotonic() < deadline:
            try:
                fresh = serve.ingress_addresses("ing", timeout=10)
            except Exception:  # noqa: BLE001 — replacement still starting
                fresh = []
            if fresh and fresh[0] != addr:
                new_addr = fresh[0]
                break
            time.sleep(0.5)
        assert new_addr, "ingress replica was not replaced"

        # the replacement restored the depleted bucket: still shed
        assert one(False, new_addr) is False
    finally:
        GLOBAL_CONFIG.serve_ingress_bucket_snapshot_period_s = old_period
        serve.shutdown()
        ray_tpu.shutdown()
