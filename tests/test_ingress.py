"""ISSUE 12: overload-safe multi-tenant ingress — the HTTP/SSE front
door with per-tenant fairness, shed-before-queue, and graceful
degradation.

Layers under test:

* policy units — cost-denominated :class:`TokenBucket` (deterministic
  via injected clocks), the :func:`shed_verdict` priority ladder, and
  the tenant→replica rendezvous hash;
* client-disconnect propagation — an HTTP client that goes away
  mid-stream must reach ``engine.cancel()``: KV blocks freed, the
  request counted cancelled, ``total_admitted`` NOT re-counted
  (pre-PR the producer decoded the whole stream for nobody);
* shed == never-admitted — the ingress shed count and the engine's
  ``total_admitted`` reconcile EXACTLY: a 429 provably consumed zero
  engine queue slots;
* router hardening — a gossip-capable deployment whose signals all went
  stale falls back with ``policy="stale_fallback"``, split from the
  plain pow-2 label;
* the loadgen harness E2E — a seeded :mod:`ray_tpu.serve.loadgen` trace
  replayed through the real HTTP door, scored against the SLO ledger.

The cluster tests here share ONE module-scoped cluster (they only need
driver-side state; ``serve.shutdown()`` between tests resets the data
plane). Tests that must stage env/config BEFORE ``ray_tpu.init`` — the
chaos env plan and the bucket-snapshot period — live in
``test_ingress_chaos.py`` with private per-test clusters.
"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.ingress import (
    CLASS_PRIORITY,
    IngressConfig,
    IngressShedError,
    TenantPolicy,
    TokenBucket,
    http_stream,
    pick_ingress,
    shed_verdict,
)

pytest.importorskip("jax")

import jax  # noqa: E402

from ray_tpu.inference.engine import EngineConfig, InferenceEngine  # noqa: E402
from ray_tpu.models.llama import LlamaConfig, init_params  # noqa: E402


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.tiny()


@pytest.fixture(scope="module")
def params(cfg):
    return init_params(cfg, jax.random.PRNGKey(0))


_EC = dict(
    num_blocks=64, block_size=8, prefill_buckets=(8, 32),
    decode_buckets=(1, 8), max_decode_batch=8, max_new_tokens_default=8,
)


@pytest.fixture(scope="module")
def ingress_cluster():
    """One cluster for every serve-integration test in this module —
    each test still deploys its own apps and tears them down with
    ``serve.shutdown()``, but the runtime processes are shared."""
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


# ---------------------------------------------------------------------------
# policy units (no cluster, no jax needed beyond the import gate)


def test_token_bucket_refill_and_retry_after():
    b = TokenBucket(rate=10.0, burst=100.0)
    t0 = b.stamp
    assert b.try_take(60, now=t0) == 0.0          # burst covers it
    assert b.try_take(60, now=t0) > 0.0           # 40 left: refused
    assert b.level == pytest.approx(40.0)         # refusal takes nothing
    # the quoted wait is exact: need 20 more units at 10/s = 2s
    assert b.try_take(60, now=t0) == pytest.approx(2.0)
    assert b.try_take(60, now=t0 + 2.0) == 0.0    # honest Retry-After
    # a single request above the whole burst is quoted against the cap
    # (servable, just slowly), then drives the bucket negative
    big = TokenBucket(rate=10.0, burst=50.0)
    t = big.stamp
    assert big.try_take(500, now=t) == 0.0
    assert big.level == pytest.approx(-450.0)
    wait = big.try_take(500, now=t)
    assert wait == pytest.approx(50.0)            # refill a FULL bucket


def test_shed_verdict_priority_ladder():
    cfg = IngressConfig(
        shed_outstanding_per_replica=100.0, shed_queue_fraction=0.5
    )
    # no fresh gossip → never shed blind
    assert shed_verdict({"reporting": 0, "outstanding_tokens": 9e9}, 0, cfg) is None
    # load ladder: batch sheds at >1x, standard >2x, interactive >3x
    p = {"reporting": 2, "outstanding_tokens": 300.0, "queue_depth": 0,
         "max_queue_depth": 256}
    assert shed_verdict(p, CLASS_PRIORITY["batch"], cfg) == "load"
    assert shed_verdict(p, CLASS_PRIORITY["standard"], cfg) is None
    p2 = dict(p, outstanding_tokens=500.0)
    assert shed_verdict(p2, CLASS_PRIORITY["standard"], cfg) == "load"
    assert shed_verdict(p2, CLASS_PRIORITY["interactive"], cfg) is None
    assert shed_verdict(dict(p, outstanding_tokens=700.0),
                        CLASS_PRIORITY["interactive"], cfg) == "load"
    # queue watermark: below-top classes shed at the fraction, everyone
    # sheds once the queues are actually full
    q = {"reporting": 2, "outstanding_tokens": 0.0, "queue_depth": 128,
         "max_queue_depth": 256}
    assert shed_verdict(q, CLASS_PRIORITY["standard"], cfg) == "queue_pressure"
    assert shed_verdict(q, CLASS_PRIORITY["interactive"], cfg) is None
    qfull = dict(q, queue_depth=256)
    assert shed_verdict(qfull, CLASS_PRIORITY["interactive"], cfg) == "queue_pressure"
    # disabled load watermark
    off = IngressConfig(shed_outstanding_per_replica=0.0)
    assert shed_verdict(p2, 0, off) is None


def test_pick_ingress_rendezvous_stable_and_spread():
    addrs = [f"127.0.0.1:{8000 + i}" for i in range(4)]
    picks = {t: pick_ingress(t, addrs) for t in (f"tenant-{i}" for i in range(64))}
    # deterministic: same tenant -> same door, independent of list order
    for t, a in picks.items():
        assert pick_ingress(t, list(reversed(addrs))) == a
    # population spreads over every door
    assert len(set(picks.values())) == len(addrs)
    # removing a door only moves the tenants that were behind it
    survivors = addrs[1:]
    moved = sum(
        1 for t, a in picks.items() if pick_ingress(t, survivors) != a
    )
    assert moved == sum(1 for a in picks.values() if a == addrs[0])
    with pytest.raises(ValueError):
        pick_ingress("t", [])


# ---------------------------------------------------------------------------
# serve integration: disconnect-cancel + exact shed reconciliation


def _run_llm_and_ingress(cfg, ing_cfg, *, llm_replicas=1, ing_replicas=1,
                         ing_name="ing"):
    dep = serve.llm_deployment(
        cfg, engine=EngineConfig(**_EC), name="llm", num_replicas=llm_replicas,
        route_prefix="/llm", ray_actor_options={"num_cpus": 0.25},
    )
    handle = serve.run(dep.bind())
    serve.run(
        serve.ingress_deployment(
            "llm", ing_cfg, name=ing_name, num_replicas=ing_replicas,
        ).bind(),
        name=ing_name,
    )
    return handle, serve.ingress_addresses(ing_name)


def test_http_ingress_disconnect_shed_and_reconcile(cfg, params, ingress_cluster):
    """One cluster, three gates: (1) SSE streams are byte-exact vs a
    local reference engine; (2) a client disconnect mid-stream reaches
    engine.cancel() — blocks freed, total_admitted NOT re-counted; (3)
    per-tenant rate shedding reconciles EXACTLY with the engine's
    admission counter (shed == never admitted), and serve.status()
    surfaces the shed/queue pressure."""
    ing_cfg = IngressConfig(
        target="llm",
        tenants={
            "abuser": TenantPolicy(rate=2.0, burst=50.0, tenant_class="batch"),
            "vip": TenantPolicy(tenant_class="interactive"),
        },
    )
    try:
        handle, addrs = _run_llm_and_ingress(cfg, ing_cfg)
        addr = addrs[0]

        def estats():
            return ray_tpu.get(handle.method("engine_stats")(), timeout=60)

        ref = InferenceEngine(cfg, params, EngineConfig(**_EC)).start()
        try:
            expected = list(ref.generate([3, 7, 11, 5], max_new_tokens=6))
        finally:
            ref.stop()

        # -- 1. greedy SSE roundtrip is byte-exact
        toks = list(http_stream(
            addr, {"prompt": [3, 7, 11, 5], "max_new_tokens": 6}, tenant="vip",
        ))
        assert toks == expected

        # -- 2. client disconnect mid-stream → engine.cancel()
        base = estats()["scheduler"]["total_admitted"]
        gen = http_stream(
            addr, {"prompt": [3, 7, 11], "max_new_tokens": 48}, tenant="vip",
        )
        assert next(gen) is not None and next(gen) is not None
        gen.close()  # the HTTP connection drops here
        deadline = time.monotonic() + 30
        s = None
        while time.monotonic() < deadline:
            s = estats()
            if (
                s["scheduler"]["running"] == 0
                and s["blocks"]["used_blocks"] == 0
                and s["scheduler"]["queue_depth"] == 0
            ):
                break
            time.sleep(0.2)
        assert s["scheduler"]["running"] == 0, s["scheduler"]
        assert s["blocks"]["used_blocks"] == 0, s["blocks"]
        # the cancelled request was admitted ONCE and never re-counted
        assert s["scheduler"]["total_admitted"] == base + 1, s["scheduler"]

        # -- 3. rate-limit shedding reconciles exactly with admission.
        # abuser cost/request = 4 + 8 = 12 against burst 50, refill 2/s:
        # ~4 admitted, the rest shed with an honest Retry-After
        base = estats()["scheduler"]["total_admitted"]
        ok, shed, retry_afters = 0, 0, []
        for _ in range(12):
            try:
                out = list(http_stream(
                    addr, {"prompt": [9, 2, 4, 6], "max_new_tokens": 8},
                    tenant="abuser",
                ))
                assert len(out) == 8
                ok += 1
            except IngressShedError as e:
                assert e.reason == "rate_limit"
                retry_afters.append(e.retry_after)
                shed += 1
        assert ok >= 1 and shed >= 1, (ok, shed)
        assert all(r > 0 for r in retry_afters)
        # EXACT reconcile: every 200 is one admission, every 429 is zero
        assert estats()["scheduler"]["total_admitted"] == base + ok
        # operators see it in serve.status() without scraping /metrics
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            st = serve.status()
            if st["ing"].get("shed_total", 0) >= shed:
                break
            time.sleep(0.25)
        assert st["ing"]["shed_total"] == shed, st["ing"]
        for key in ("queue_depth", "outstanding_tokens", "shed_total"):
            assert key in st["llm"] and key in st["ing"]

        # -- 4. malformed request → 400, counted, never forwarded
        import urllib.error
        import urllib.request
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                f"http://{addr}/generate", data=b'{"nope": 1}',
                headers={"Content-Type": "application/json"},
            ), timeout=30)
        assert ei.value.code == 400

        # -- 5. ISSUE 15 SLO-ledger books over the same traffic: the
        # ingress conservation identity (seen == shed + bad_request +
        # forwarded) and the engine identity (submitted == finished +
        # failed + cancelled + in-flight) both balance EXACTLY through
        # serve.slo_report() — sheds, the disconnect-cancel, and the
        # 400 all landed in exactly one bucket each
        from ray_tpu.observability import slo as _slo

        deadline = time.monotonic() + 20
        while True:
            rep = serve.slo_report()
            books = [b for d in rep["deployments"].values() for b in d["books"]]
            if books and all(b["balanced"] for b in books):
                break
            assert time.monotonic() < deadline, books
            time.sleep(0.5)
        ing_books = [b for b in books if b.get("kind") == "ingress"]
        eng_books = [b for b in books if b.get("kind") == "engine"]
        assert ing_books and eng_books, books
        ib = ing_books[0]
        assert ib["shed"] == shed and ib["bad_request"] == 1, ib
        assert ib["seen"] == ib["shed"] + ib["bad_request"] + ib["forwarded"]
        assert _slo.books_balanced(ib) and _slo.books_balanced(eng_books[0])
        # the aggregated histograms carry the classes the door stamped
        llm = rep["deployments"]["llm"]
        assert llm["ttft_s"]["count"] > 0 and llm["by_class"], llm
        assert "interactive" in llm["by_class"] or "batch" in llm["by_class"]
        # shed requests left flagged ingress flight-recorder entries
        sheds_rec = [
            r for r in rep["flight_recorder"]
            if "shed" in (r.get("flags") or ())
        ]
        assert sheds_rec, rep["flight_recorder"][:5]
    finally:
        serve.shutdown()


def test_queue_fraction_shed_spares_interactive(cfg, params, ingress_cluster):
    """Graceful degradation, deterministically: shed_queue_fraction=0.0
    sheds every below-top class the moment fresh engine gossip exists,
    while interactive traffic still flows — the priority ladder is
    observable end to end through HTTP status codes."""
    ing_cfg = IngressConfig(
        target="llm",
        shed_queue_fraction=0.0,
        tenants={
            "bg": TenantPolicy(tenant_class="batch"),
            "vip": TenantPolicy(tenant_class="interactive"),
        },
    )
    try:
        _handle, addrs = _run_llm_and_ingress(cfg, ing_cfg, ing_name="ing")
        addr = addrs[0]
        # prime: one vip request starts the ingress router's long-poll;
        # wait until the gossip actually reached it (pressure reporting)
        list(http_stream(addr, {"prompt": [1, 2, 3], "max_new_tokens": 2},
                         tenant="vip"))
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            try:
                list(http_stream(
                    addr, {"prompt": [5, 6], "max_new_tokens": 2}, tenant="bg",
                ))
            except IngressShedError as e:
                assert e.reason == "queue_pressure"
                break
            time.sleep(0.25)
        else:
            pytest.fail("batch tenant was never shed on queue pressure")
        # interactive still flows under the same pressure signal
        out = list(http_stream(
            addr, {"prompt": [1, 2, 3, 4], "max_new_tokens": 4}, tenant="vip",
        ))
        assert len(out) == 4
    finally:
        serve.shutdown()


# ---------------------------------------------------------------------------
# router hardening: stale gossip falls back attributably


def test_router_stale_gossip_counts_stale_fallback(ingress_cluster):
    """A gossip-capable deployment (no jax needed — any callable with
    routing_stats()) whose signals all age past the TTL must fall back
    to pow-2 under the DISTINCT policy label, so a load test can tell
    'scored path engaged' from 'gossip was stale the whole run'."""
    from ray_tpu.core.config import GLOBAL_CONFIG
    from ray_tpu.observability.rpc_metrics import ROUTER_DECISIONS

    old_ttl = GLOBAL_CONFIG.serve_routing_stats_ttl_s
    try:
        @serve.deployment(num_replicas=2, ray_actor_options={"num_cpus": 0.1})
        class Gossipy:
            def routing_stats(self):
                return {"outstanding_tokens": 0.0, "queue_depth": 0,
                        "max_queue_depth": 8}

            def __call__(self, x):
                return x

        handle = serve.run(Gossipy.bind(), name="Gossipy")
        ctrl = ray_tpu.get_actor("__serve_controller__")
        ray_tpu.get(
            ctrl.wait_status.remote("Gossipy", min_replicas=2, timeout_s=60),
            timeout=90,
        )
        router = handle._router

        def decisions(policy):
            return ROUTER_DECISIONS._values.get(("Gossipy", policy), 0)

        # wait for fresh gossip → the scored path engages
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            router.choose_replica()
            if decisions("affinity") > 0:
                break
            time.sleep(0.2)
        assert decisions("affinity") > 0, dict(ROUTER_DECISIONS._values)
        # pressure rollup sees both replicas reporting
        p = router.cluster_pressure()
        assert p["reporting"] == 2 and p["max_queue_depth"] == 16, p

        # now every signal is stale by definition: TTL → 0
        GLOBAL_CONFIG.serve_routing_stats_ttl_s = 1e-9
        before_stale = decisions("stale_fallback")
        before_pow2 = decisions("pow2")
        for _ in range(5):
            router.choose_replica()
        assert decisions("stale_fallback") >= before_stale + 5
        assert decisions("pow2") == before_pow2  # split, not lumped
        assert router.cluster_pressure()["reporting"] == 0
    finally:
        GLOBAL_CONFIG.serve_routing_stats_ttl_s = old_ttl
        serve.shutdown()


# ---------------------------------------------------------------------------
# the SLO-autopilot load harness, end to end through the real HTTP door


def test_loadgen_trace_replays_through_ingress(cfg, params, ingress_cluster):
    """A seeded :mod:`ray_tpu.serve.loadgen` trace replays through a
    real ingress deployment with ZERO errors and scores against the SLO
    ledger: the harness's client-side records reconcile with the door's
    terminal outcomes, every record carries the request_id the trace
    stamped (the flight-recorder join key), and the score block carries
    attainment + the one-line repro."""
    from ray_tpu.serve import loadgen

    spec = loadgen.LoadSpec(
        seed=20260806,
        duration_s=2.0,
        base_rate_rps=5.0,
        burst_factor=2.0,
        n_tenants=3,
        prompt_min=3,
        prompt_max=12,
        prefix_len=4,
        output_min=2,
        output_max=4,
    )
    trace = loadgen.build_trace(spec)
    assert trace, "seed 20260806 must produce a non-empty 2s trace"
    # the replay contract behind 'reproduces from one logged line'
    assert [r.request_id for r in loadgen.build_trace(spec)] == [
        r.request_id for r in trace
    ]

    ing_cfg = IngressConfig(target="llm", default_rate=1e6, default_burst=1e6)
    try:
        _handle, addrs = _run_llm_and_ingress(cfg, ing_cfg, ing_name="ing")
        run = loadgen.run_trace(
            trace,
            spec=spec,
            addresses=addrs,
            time_scale=0.25,
            timeout_s=60.0,
            status_fn=serve.status,
        )
        assert len(run.records) == len(trace)
        bad = [r for r in run.records if r["outcome"] != "ok"]
        assert not bad, bad

        s = loadgen.score(
            run,
            ttft_slo_s=30.0,
            itl_slo_s=30.0,
            report=serve.slo_report(),
            status=serve.status(),
        )
        assert s["ok"] == len(trace) and s["errors"] == 0 and s["shed"] == 0
        assert s["ttft_attainment"] == 1.0 and s["itl_attainment"] == 1.0
        assert s["by_class"], s
        assert f"LOADGEN_SEED={spec.seed}" in s["repro"]
        assert s["miss_attribution"] == {}, s["miss_attribution"]
        # the run sampled the live cluster status on a timer
        assert run.samples and "llm" in run.samples[-1][1]
    finally:
        serve.shutdown()
