"""Ingress tests that REQUIRE a private per-test cluster.

Split out of test_ingress.py (which shares one module-scoped cluster):
these two stage state that must exist BEFORE ``ray_tpu.init`` — the
chaos env plan (``RAY_TPU_testing_*`` reaches workers only via driver
env → daemon at init → worker at spawn) and the bucket-snapshot period
(GLOBAL_CONFIG is forked into worker processes at init). A shared
cluster can't replay that staging, so each test owns its lifecycle.

* the many-tenant chaos E2E — heavy-tailed tenants + one abusive tenant
  + a seeded mid-run replica kill: the abusive tenant is shed (429s),
  well-behaved tenants see ZERO client-visible errors and byte-exact
  greedy streams (the PR 10 resumable path makes the kill invisible
  through HTTP), and the run reproduces from the logged chaos env line
  alone;
* bucket persistence — per-tenant token-bucket levels survive an
  ingress replica kill via the controller snapshot/restore path.
"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.ingress import (
    IngressConfig,
    IngressShedError,
    TenantPolicy,
    http_stream,
    pick_ingress,
)

pytest.importorskip("jax")

import jax  # noqa: E402

from ray_tpu.inference.engine import EngineConfig, InferenceEngine  # noqa: E402
from ray_tpu.models.llama import LlamaConfig, init_params  # noqa: E402


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.tiny()


@pytest.fixture(scope="module")
def params(cfg):
    return init_params(cfg, jax.random.PRNGKey(0))


_EC = dict(
    num_blocks=64, block_size=8, prefill_buckets=(8, 32),
    decode_buckets=(1, 8), max_decode_batch=8, max_new_tokens_default=8,
)


def _run_llm_and_ingress(cfg, ing_cfg, *, llm_replicas=1, ing_replicas=1,
                         ing_name="ing"):
    dep = serve.llm_deployment(
        cfg, engine=EngineConfig(**_EC), name="llm", num_replicas=llm_replicas,
        route_prefix="/llm", ray_actor_options={"num_cpus": 0.25},
    )
    handle = serve.run(dep.bind())
    serve.run(
        serve.ingress_deployment(
            "llm", ing_cfg, name=ing_name, num_replicas=ing_replicas,
        ).bind(),
        name=ing_name,
    )
    return handle, serve.ingress_addresses(ing_name)


# ---------------------------------------------------------------------------
# the ISSUE 12 acceptance gate: many tenants + one abuser + seeded kill


@pytest.mark.chaos
def test_e2e_many_tenant_chaos_slos_hold(cfg, params):
    """ISSUE 12 gate: heavy-tailed tenants, one abusive tenant
    saturating its bucket, TWO ingress doors over TWO engine replicas,
    and a seeded ReplicaFaultPlan SIGKILLing engines mid-decode. The
    abusive tenant is shed (429 + Retry-After); every well-behaved
    request streams the byte-exact greedy sequence with ZERO
    client-visible errors (the kill is absorbed by the resumable-stream
    tier); shed requests never reached an engine (ingress-side
    conservation); and the whole schedule reproduces from the chaos env
    line the conftest repro helper prints."""
    import os
    import random

    from ray_tpu.util.chaos import ReplicaFaultPlan

    SPEC, SEED = "kill_mid_decode:1.0:25:1", 20260804
    n_tenants, per_tenant, max_new = 4, 5, 6

    # heavy-tailed prompt lengths (bounded Pareto), per-tenant shared
    # system prefix so the affinity scorer has something to pin
    rnd = random.Random(1234)
    prefixes = {
        t: [10 + t] * (8 + 2 * t) for t in range(n_tenants)
    }
    prompts = {}
    for t in range(n_tenants):
        for i in range(per_tenant):
            tail_len = min(24, max(2, int(rnd.paretovariate(1.2))))
            tail = [rnd.randrange(1, 250) for _ in range(tail_len)]
            prompts[(t, i)] = prefixes[t] + tail

    # expected sequences from an undisturbed local engine (greedy →
    # deterministic continuation makes the killed-and-resumed streams
    # byte-exact). Computed BEFORE the env plan is exported: see
    # test_stream_resume for the self-SIGKILL rationale.
    ref = InferenceEngine(cfg, params, EngineConfig(**_EC)).start()
    try:
        expected = {
            k: list(ref.generate(p, max_new_tokens=max_new))
            for k, p in prompts.items()
        }
    finally:
        ref.stop()

    os.environ["RAY_TPU_testing_replica_chaos"] = SPEC
    os.environ["RAY_TPU_testing_replica_chaos_seed"] = str(SEED)
    ray_tpu.init(num_cpus=4)
    try:
        # the conftest repro contract (same as PR 10's tests): a failure
        # here prints ONE env line that replays this exact schedule
        from conftest import _chaos_repro_line

        line = _chaos_repro_line("tests/test_ingress_chaos.py::e2e")
        assert line and SPEC in line and str(SEED) in line, line

        ing_cfg = IngressConfig(
            target="llm",
            shed_outstanding_per_replica=2048.0,
            tenants={
                "abuser": TenantPolicy(
                    rate=3.0, burst=40.0, tenant_class="batch"
                ),
                **{
                    f"tenant-{t}": TenantPolicy(tenant_class="interactive")
                    for t in range(n_tenants)
                },
            },
        )
        _handle, addrs = _run_llm_and_ingress(
            cfg, ing_cfg, llm_replicas=2, ing_replicas=2, ing_name="ing",
        )
        ctrl = ray_tpu.get_actor("__serve_controller__")
        ray_tpu.get(
            ctrl.wait_status.remote("llm", min_replicas=2, timeout_s=90),
            timeout=120,
        )

        results, errors, ttfts = {}, {}, []
        shed_count, abuser_ok = [0], [0]
        lock = threading.Lock()

        def tenant_load(t):
            tenant = f"tenant-{t}"
            addr = pick_ingress(tenant, addrs)
            for i in range(per_tenant):
                key = (t, i)
                try:
                    t0 = time.monotonic()
                    first, toks = None, []
                    for tok in http_stream(
                        addr,
                        {"prompt": prompts[key], "max_new_tokens": max_new},
                        tenant=tenant, connect_timeout=150.0,
                    ):
                        if first is None:
                            first = time.monotonic() - t0
                        toks.append(tok)
                    with lock:
                        results[key] = toks
                        ttfts.append(first if first is not None else 0.0)
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors[key] = e

        def abuser_load():
            addr = pick_ingress("abuser", addrs)
            for _ in range(30):
                try:
                    list(http_stream(
                        addr, {"prompt": [7, 7, 7, 7], "max_new_tokens": 8},
                        tenant="abuser", connect_timeout=150.0,
                    ))
                    with lock:
                        abuser_ok[0] += 1
                except IngressShedError as e:
                    assert e.retry_after > 0
                    with lock:
                        shed_count[0] += 1
                time.sleep(0.05)

        threads = [
            threading.Thread(target=tenant_load, args=(t,))
            for t in range(n_tenants)
        ] + [threading.Thread(target=abuser_load)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=150)
        assert not any(th.is_alive() for th in threads), "load never finished"

        # -- SLOs: zero client-visible errors, byte-exact streams
        assert not errors, errors
        bad = {k: (results[k], expected[k]) for k in expected
               if results.get(k) != expected[k]}
        assert not bad, bad
        # bounded TTFT even across the kill (p99 over 20 streams = max)
        assert max(ttfts) < 60.0, sorted(ttfts)[-3:]

        # -- the abuser was actually shed, and sheds never reached an
        # engine: at the door, requests either forwarded or 429'd
        assert shed_count[0] > 0, (shed_count, abuser_ok)
        replicas = ray_tpu.get(ctrl.get_replicas.remote("ing"), timeout=60)
        dbg = [
            ray_tpu.get(
                r.handle_request.remote("debug_stats", [], {}, ""), timeout=60
            )
            for r in replicas
        ]
        total_ok = sum(
            n for d in dbg for k, n in d["outcomes"].items()
            if k.endswith(":ok")
        )
        total_shed = sum(d["shed_total"] for d in dbg)
        forwarded = sum(d["forwarded_total"] for d in dbg)
        n_requests = n_tenants * per_tenant + 30
        assert total_ok + total_shed == n_requests, (dbg, n_requests)
        assert forwarded == n_requests - total_shed, (forwarded, total_shed)
        assert total_ok == n_tenants * per_tenant + abuser_ok[0]

        # -- the kill provably landed mid-run and was absorbed: the
        # ingress routers resumed streams, the controller replaced the
        # dead engine replica(s)
        resumes = sum(d["stream_resumes"].get("llm", 0) for d in dbg)
        assert resumes > 0, dbg
        st = ray_tpu.get(
            ctrl.wait_status.remote("llm", min_replicas=2, timeout_s=120),
            timeout=150,
        )
        assert st["replicas"] == 2 and st["restarts"]["death"] >= 1, st
        # the scored (affinity) path engaged under load at the doors
        affinity = sum(
            d["router_decisions"].get("llm:affinity", 0) for d in dbg
        )
        assert affinity > 0, [d["router_decisions"] for d in dbg]

        # -- reproducibility: the seeded schedule is a pure function of
        # (seed, consult order) — the logged env line replays it
        p1, p2 = ReplicaFaultPlan(SPEC, SEED), ReplicaFaultPlan(SPEC, SEED)
        phases = ["prefill"] * 4 + ["decode"] * 30
        s1 = [p1.consult(p) for p in phases]
        assert s1 == [p2.consult(p) for p in phases]
        assert p1.injections == 1
    finally:
        os.environ.pop("RAY_TPU_testing_replica_chaos", None)
        os.environ.pop("RAY_TPU_testing_replica_chaos_seed", None)
        from ray_tpu.core.config import GLOBAL_CONFIG

        GLOBAL_CONFIG.testing_replica_chaos = ""
        GLOBAL_CONFIG.testing_replica_chaos_seed = 0
        serve.shutdown()
        ray_tpu.shutdown()


def test_bucket_state_survives_ingress_replica_restart(cfg, params):
    """ISSUE 13 satellite: per-tenant token-bucket fill levels are
    snapshot to the serve controller on a timer and restored by a
    replacement replica — killing the door mid-depletion must NOT hand
    the tenant a fresh burst. Pre-persistence, every restart reset every
    tenant's budget (buckets were per-replica memory)."""
    from ray_tpu.core.config import GLOBAL_CONFIG

    # near-zero refill: any admission after the restart can only come
    # from a (wrongly) refilled burst, never from honest refill. Burst
    # covers exactly two requests of cost 4 + 8 = 12.
    ing_cfg = IngressConfig(
        target="llm",
        tenants={"miser": TenantPolicy(rate=0.001, burst=24.0)},
    )
    old_period = GLOBAL_CONFIG.serve_ingress_bucket_snapshot_period_s
    GLOBAL_CONFIG.serve_ingress_bucket_snapshot_period_s = 0.25
    ray_tpu.init(num_cpus=4)
    try:
        _handle, addrs = _run_llm_and_ingress(cfg, ing_cfg, ing_name="ing")
        addr = addrs[0]

        def one(expect_ok: bool, a: str) -> bool:
            try:
                out = list(http_stream(
                    a, {"prompt": [9, 2, 4, 6], "max_new_tokens": 8},
                    tenant="miser", connect_timeout=120.0,
                ))
                assert len(out) == 8
                return True
            except IngressShedError as e:
                assert e.reason == "rate_limit"
                return False

        # deplete the bucket: two admissions, third sheds
        assert one(True, addr) is True
        assert one(True, addr) is True
        assert one(False, addr) is False
        time.sleep(4 * GLOBAL_CONFIG.serve_ingress_bucket_snapshot_period_s)

        # kill the door; the controller replaces it
        ctrl = ray_tpu.get_actor("__serve_controller__")
        victim = ray_tpu.get(ctrl.get_replicas.remote("ing"), timeout=30)[0]
        ray_tpu.kill(victim)
        deadline = time.monotonic() + 90
        new_addr = None
        while time.monotonic() < deadline:
            try:
                fresh = serve.ingress_addresses("ing", timeout=10)
            except Exception:  # noqa: BLE001 — replacement still starting
                fresh = []
            if fresh and fresh[0] != addr:
                new_addr = fresh[0]
                break
            time.sleep(0.5)
        assert new_addr, "ingress replica was not replaced"

        # the replacement restored the depleted bucket: still shed
        assert one(False, new_addr) is False
    finally:
        GLOBAL_CONFIG.serve_ingress_bucket_snapshot_period_s = old_period
        serve.shutdown()
        ray_tpu.shutdown()
