"""Job submission: REST submit/status/logs/stop + supervisor lifecycle
(reference ``dashboard/modules/job/``: ``job_manager.py:59``,
``job_supervisor.py:54``, ``sdk.py:125``)."""

import time

import pytest

import ray_tpu
from ray_tpu.job import JobSubmissionClient, start_job_server, stop_job_server


@pytest.fixture(scope="module")
def client():
    ray_tpu.init(num_cpus=4)
    server = start_job_server(port=0)  # ephemeral port
    yield JobSubmissionClient(f"http://127.0.0.1:{server.port}")
    stop_job_server()
    ray_tpu.shutdown()


def test_job_succeeds_with_logs(client):
    job_id = client.submit_job(
        entrypoint="python -c \"print('hello from job'); print('line two')\""
    )
    assert client.get_job_status(job_id) in ("PENDING", "RUNNING", "SUCCEEDED")
    status = client.wait_until_terminal(job_id, timeout=120)
    assert status == "SUCCEEDED"
    logs = client.get_job_logs(job_id)
    assert "hello from job" in logs and "line two" in logs
    info = client.get_job_info(job_id)
    assert info["entrypoint"].startswith("python -c")
    assert info["end_time"] >= info["start_time"]


def test_job_failure_surfaces(client):
    job_id = client.submit_job(
        entrypoint="python -c \"import sys; print('about to die'); sys.exit(3)\""
    )
    assert client.wait_until_terminal(job_id, timeout=120) == "FAILED"
    info = client.get_job_info(job_id)
    assert "code 3" in info["message"]
    assert "about to die" in client.get_job_logs(job_id)


def test_job_entrypoint_retries(client):
    """A flaky entrypoint succeeds on retry (reference
    entrypoint_num_retries): first attempt fails on a marker file."""
    import tempfile, os

    marker = tempfile.mktemp()
    script = (
        "import os,sys;"
        f"p={marker!r};"
        "first=not os.path.exists(p);"
        "open(p,'w').write('x');"
        "print('attempt', 'first' if first else 'second');"
        "sys.exit(1 if first else 0)"
    )
    job_id = client.submit_job(
        entrypoint=f'python -c "{script}"', entrypoint_num_retries=2
    )
    assert client.wait_until_terminal(job_id, timeout=120) == "SUCCEEDED"
    logs = client.get_job_logs(job_id)
    assert "attempt first" in logs and "attempt second" in logs
    assert "entrypoint retry 1/2" in logs
    os.unlink(marker)


def test_job_stop(client):
    job_id = client.submit_job(
        entrypoint="python -c \"import time; print('sleeping',flush=True); time.sleep(600)\""
    )
    deadline = time.monotonic() + 60
    while client.get_job_status(job_id) != "RUNNING":
        assert time.monotonic() < deadline
        time.sleep(0.2)
    # wait for the subprocess to actually print (it exists by then)
    while "sleeping" not in client.get_job_logs(job_id):
        assert time.monotonic() < deadline
        time.sleep(0.2)
    assert client.stop_job(job_id)
    assert client.wait_until_terminal(job_id, timeout=60) == "STOPPED"


def test_job_runs_cluster_workload(client):
    """The entrypoint connects back to THIS cluster via the injected
    RAY_TPU_ADDRESS and talks to an actor the SUBMITTING driver created
    — proof it joined this cluster rather than booting its own."""
    import ray_tpu

    @ray_tpu.remote(name="job_witness", lifetime="detached", num_cpus=0)
    class Witness:
        def ping(self):
            return "seen-by-job"

    w = Witness.remote()
    ns = ray_tpu.get_runtime_context().namespace
    script = (
        "import os,ray_tpu;"
        "assert os.environ.get('RAY_TPU_ADDRESS'), 'no cluster address injected';"
        "ray_tpu.init();"  # address from RAY_TPU_ADDRESS
        f"a=ray_tpu.get_actor('job_witness', namespace='{ns}');"
        "print('witness', ray_tpu.get(a.ping.remote(), timeout=60));"
        "f=ray_tpu.remote(lambda x: x*7);"
        "print('answer', ray_tpu.get(f.remote(6), timeout=60));"
        "ray_tpu.shutdown()"
    )
    job_id = client.submit_job(entrypoint=f'python -c "{script}"')
    try:
        assert client.wait_until_terminal(job_id, timeout=180) == "SUCCEEDED", (
            client.get_job_logs(job_id)
        )
        logs = client.get_job_logs(job_id)
        assert "witness seen-by-job" in logs
        assert "answer 42" in logs
    finally:
        ray_tpu.kill(w)


def test_job_list_and_delete(client):
    job_id = client.submit_job(entrypoint="python -c \"print('quick')\"")
    client.wait_until_terminal(job_id, timeout=120)
    assert any(j["job_id"] == job_id for j in client.list_jobs())
    assert client.delete_job(job_id)
    assert all(j["job_id"] != job_id for j in client.list_jobs())
    with pytest.raises(RuntimeError, match="404"):
        client.get_job_status(job_id)


def test_duplicate_submission_id_rejected(client):
    job_id = client.submit_job(entrypoint="python -c \"print('a')\"")
    with pytest.raises(RuntimeError, match="409"):
        client.submit_job(entrypoint="echo x", submission_id=job_id)
    client.wait_until_terminal(job_id, timeout=120)


def test_tail_job_logs(client):
    script = (
        "import time\n"
        "for i in range(5):\n"
        "    print('tick', i, flush=True)\n"
        "    time.sleep(0.3)\n"
    )
    job_id = client.submit_job(entrypoint=f"python -c \"{script}\"")
    chunks = list(client.tail_job_logs(job_id, poll_s=0.2))
    full = "".join(chunks)
    for i in range(5):
        assert f"tick {i}" in full
    assert len(chunks) >= 2  # actually incremental, not one dump
