"""ISSUE 17: cluster-wide KV prefix tier — integrity-checked fault-in,
live decode migration, warm replica restart.

Layers under test, cheapest first:

* ``KvTierFaultPlan`` — seeded grammar/phase/skip-window/cap semantics
  and the master-seed (``testing_chaos_seed``) derivation fold;
* spill-vs-drop books balance — ``PagedBlockManager`` eviction and the
  tier write-back are ONE policy decision point: every evicted indexed
  block is exactly one of spilled / dropped, referenced blocks are
  never offered, and a broken policy hook degrades to drop;
* daemon-less tier registry — publish/fetch/delete/list roundtrip via
  the inline-descriptor fallback, with the chaos modes driving the
  integrity gate (corrupt payload refused, missing/stale fall through
  fast);
* router tier directory — live-holder one-hop retraction vs dead-holder
  TTL retention, and the chain-digest prefix matcher that builds the
  ``kv_tier`` request spec;
* cluster-free engine/server roundtrips — prefill write-back on one
  engine faulted in by another (byte-exact, prefix-warm), the counted
  fallback ladder under armed chaos, and drain-with-migration flushing
  prompt+generated KV for a survivor to resume from.

The one-cluster E2E chaos gate (hot replica SIGKILLed mid-decode: plan
OFF resumes via tier fault-in with ZERO replay tokens; plan armed
falls back byte-exact) lives in tests/test_stream_resume_tier.py with
the other stream-resume E2E suites.
"""

import pytest

from ray_tpu.util.chaos import KvTierFaultPlan, derive_plan_seed

pytest.importorskip("jax")

import jax  # noqa: E402

from ray_tpu.inference.engine import EngineConfig, InferenceEngine  # noqa: E402
from ray_tpu.inference.kv_cache import PagedBlockManager, _chain_digest  # noqa: E402
from ray_tpu.models.llama import LlamaConfig, init_params  # noqa: E402

#: 24 tokens = 3 full blocks at block_size 8
SHARED = [12, 7, 3, 9, 1, 5, 2, 8] * 3


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.tiny()


@pytest.fixture(scope="module")
def params(cfg):
    return init_params(cfg, jax.random.PRNGKey(0))


def _ec(**overrides):
    kw = dict(
        num_blocks=64, block_size=8, prefill_buckets=(8, 32),
        decode_buckets=(1, 4), max_decode_batch=4, max_new_tokens_default=8,
        warmup=False, kv_transfer_enabled=True, kv_tier_enabled=True,
    )
    kw.update(overrides)
    return EngineConfig(**kw)


@pytest.fixture(autouse=True)
def _clean_tier():
    """Every test starts and ends with an empty local tier and no
    surgically-armed plan — _LOCAL_TIER is process-global state."""
    from ray_tpu.inference import kv_transfer

    yield
    with kv_transfer._LOCAL_TIER_LOCK:
        kv_transfer._LOCAL_TIER.clear()
    kv_transfer.testing_tier_plan = None


def _digests(tokens, bs=8):
    """Full-block chain digests of ``tokens`` (the tier's key space)."""
    out, prev = [], b""
    for end in range(bs, len(tokens) + 1, bs):
        prev = _chain_digest(prev, tokens[end - bs : end])
        out.append(prev)
    return out


def _collect(gen):
    """Flatten LLMServer.generate's TokenChunk bursts — the serve
    router does the same before clients see individual items."""
    return [t for chunk in gen for t in chunk]


# ---------------------------------------------------------------------------
# unit: KvTierFaultPlan


def test_kv_tier_fault_plan_grammar_and_determinism():
    with pytest.raises(ValueError):
        KvTierFaultPlan("missing_block", 1)  # no prob
    with pytest.raises(ValueError):
        KvTierFaultPlan("explode:1.0", 1)  # unknown mode

    # same seed -> identical schedule over an identical consult sequence
    phases = ["fault_in"] * 6 + ["migration"] * 4 + ["fault_in"] * 6
    p1 = KvTierFaultPlan("corrupt_block:0.5:0:99", 77)
    p2 = KvTierFaultPlan("corrupt_block:0.5:0:99", 77)
    s1 = [p1.consult(ph) for ph in phases]
    assert s1 == [p2.consult(ph) for ph in phases]
    assert p1.consults == len(phases)
    # block-fault modes never fire on the migration phase
    assert all(
        v is None for v, ph in zip(s1, phases) if ph == "migration"
    )

    # skip window: param=2 skips the first two matching consults
    p = KvTierFaultPlan("missing_block:1.0:2:99", 3)
    got = [p.consult("fault_in") for _ in range(4)]
    assert got[:2] == [None, None]
    assert got[2] == ("missing_block", 2.0)

    # default cap: one injection per process, then the plan goes quiet
    p = KvTierFaultPlan("missing_block:1.0", 3)
    fired = [p.consult("fault_in") for _ in range(5)]
    assert fired.count(("missing_block", 0.0)) == 1 and p.injections == 1

    # kill_mid_migration matches ONLY the migration phase
    p = KvTierFaultPlan("kill_mid_migration:1.0", 9)
    assert p.consult("fault_in") is None
    assert p.consult("migration") == ("kill_mid_migration", 0.0)


def test_kv_tier_plan_derives_from_master_chaos_seed():
    """The composite-chaos fold: one logged master seed reproduces the
    tier plan's full schedule (conftest prints the one-line repro)."""
    master = 20260806
    seed = derive_plan_seed(master, "kv_tier")
    assert seed == derive_plan_seed(master, "kv_tier")  # stable
    assert seed != derive_plan_seed(master, "replica")  # per-label
    a = KvTierFaultPlan("missing_block:0.3:0:99", seed)
    b = KvTierFaultPlan("missing_block:0.3:0:99", seed)
    phases = ["fault_in"] * 32
    assert [a.consult(p) for p in phases] == [b.consult(p) for p in phases]


# ---------------------------------------------------------------------------
# unit: spill-vs-drop books balance (the unlocking refactor)


def _balanced(mgr):
    assert (
        mgr.prefix_evictions_total
        == mgr.prefix_spilled_total + mgr.prefix_dropped_total
    ), (mgr.prefix_evictions_total, mgr.prefix_spilled_total,
        mgr.prefix_dropped_total)


def test_spill_vs_drop_books_balance():
    """Every evicted indexed block is EXACTLY one of spilled or dropped
    (evictions == spilled + dropped at every step), the policy hook only
    ever sees unreferenced blocks, popularity decides the verdict, and
    both ``_evict_indexed_locked`` call sites — allocation-pressure LRU
    reclaim and the register cap-eviction — run the same policy."""
    T = [31, 4, 44, 18] * 2  # 8 tokens = 2 full blocks at bs 4
    offered = []

    mgr = PagedBlockManager(8, 4, prefix_cache_enabled=True)

    def hook(digest, blk, hits):
        offered.append((digest, blk, hits, mgr._ref.get(blk, 0)))
        return hits > 0  # spill popular, drop cold

    mgr.set_spill_hook(hook)

    # index two blocks, release them to the LRU
    assert mgr.grow_to("a", 8)
    assert mgr.register_prefix("a", T) == 2
    mgr.free("a")
    # one popularity hit on both blocks (9-token prompt: no COW path)
    cached, cow = mgr.acquire_prefix("b", T + [99])
    assert cached == 8 and not cow
    mgr.free("b")

    # allocation pressure: 7 blocks needed, 5 free -> reclaims both LRU
    # blocks through the ONE policy point; hits==1 -> spilled
    assert mgr.grow_to("c", 28)
    _balanced(mgr)
    assert mgr.prefix_evictions_total == 2
    assert mgr.prefix_spilled_total == 2 and mgr.prefix_dropped_total == 0
    assert [h for _, _, h, _ in offered] == [1, 1]

    # index c's blocks cold (never acquired), free, then evict under
    # pressure again: hits==0 -> dropped
    U = list(range(100, 128))  # 28 tokens, distinct from T
    assert mgr.register_prefix("c", U) == 7
    mgr.free("c")
    assert mgr.grow_to("d", 28)
    _balanced(mgr)
    assert mgr.prefix_evictions_total == 9
    assert mgr.prefix_dropped_total == 7
    mgr.free("d")

    # the hook NEVER saw a referenced block
    assert all(ref == 0 for _, _, _, ref in offered), offered

    # stats surface the split for the metrics endpoint
    st = mgr.prefix_stats()
    assert st["spilled_total"] == 2 and st["dropped_total"] == 7


def test_spill_hook_cap_eviction_and_broken_hook():
    # cap-eviction call site: prefix_cache_max_blocks forces the
    # register path itself through the policy point
    seen = []
    mgr = PagedBlockManager(8, 4, prefix_cache_enabled=True,
                            prefix_cache_max_blocks=1)
    mgr.set_spill_hook(lambda d, b, h: seen.append(b) or True)
    assert mgr.grow_to("a", 8)
    assert mgr.register_prefix("a", [1, 2, 3, 4]) == 1
    mgr.free("a")
    assert mgr.grow_to("b", 4)
    assert mgr.register_prefix("b", [9, 9, 9, 9]) == 1
    _balanced(mgr)
    assert mgr.prefix_evictions_total == 1 and mgr.prefix_spilled_total == 1
    assert len(seen) == 1
    mgr.free("b")

    # a hook that raises degrades to drop — never to a stuck pool
    mgr2 = PagedBlockManager(4, 4, prefix_cache_enabled=True)

    def broken(d, b, h):
        raise RuntimeError("policy crashed")

    mgr2.set_spill_hook(broken)
    assert mgr2.grow_to("a", 4)
    assert mgr2.register_prefix("a", [5, 6, 7, 8]) == 1
    mgr2.free("a")
    assert mgr2.grow_to("b", 12)  # needs all 3 usable -> evicts the block
    _balanced(mgr2)
    assert mgr2.prefix_dropped_total == 1 and mgr2.prefix_spilled_total == 0


# ---------------------------------------------------------------------------
# unit: daemon-less tier registry + integrity gate


def test_local_tier_roundtrip_delete_and_cap():
    import numpy as np

    from ray_tpu.core.config import GLOBAL_CONFIG
    from ray_tpu.inference import kv_transfer

    kv = np.arange(2 * 2 * 1 * 8 * 2 * 16, dtype=np.float32).reshape(
        2, 2, 1, 8, 2, 16
    )
    d1, d2, d3 = _digests([1] * 8 + [2] * 8 + [3] * 8)
    desc = kv_transfer.tier_publish(d1, kv, 8)
    assert desc is not None and desc["tier_digest"] == d1.hex()
    assert d1.hex() in kv_transfer.tier_list()

    f = kv_transfer.tier_fetch(desc)
    try:
        assert np.array_equal(f.array, kv)
    finally:
        f.close()
    # tier reads keep the source: a second fault-in still succeeds
    f2 = kv_transfer.tier_fetch(desc)
    f2.close()

    kv_transfer.tier_delete(d1.hex(), desc=desc)
    assert d1.hex() not in kv_transfer.tier_list()

    # bounded registry: oldest entry evicted at kv_tier_max_entries
    old_cap = GLOBAL_CONFIG.kv_tier_max_entries
    GLOBAL_CONFIG.kv_tier_max_entries = 2
    try:
        for d in (d1, d2, d3):
            assert kv_transfer.tier_publish(d, kv, 8) is not None
        entries = kv_transfer.tier_list()
        assert d1.hex() not in entries
        assert d2.hex() in entries and d3.hex() in entries
    finally:
        GLOBAL_CONFIG.kv_tier_max_entries = old_cap


def test_daemon_tier_popularity_eviction_hot_prefix_outlives_cold():
    """PR 19 satellite: the daemon registry's cap eviction is keyed on
    (hit count, recency), not insertion age — a hot shared prefix that
    readers keep faulting in outlives colder NEWER entries. Drives the
    real NodeDaemon registry methods on a stub (no cluster, no sockets:
    the registry touches only its own dicts + store.delete)."""
    import asyncio
    from collections import OrderedDict

    from ray_tpu.core.config import GLOBAL_CONFIG
    from ray_tpu.core.node_daemon import NodeDaemon

    class _Stub:
        class store:  # noqa: N801 — _kv_tier_drop_locked calls .delete
            @staticmethod
            def delete(oid):
                pass

    stub = _Stub()
    stub._kv_tier = OrderedDict()
    stub._last_kv_tier_sweep = 0.0
    stub._kv_tier_sweep = NodeDaemon._kv_tier_sweep.__get__(stub)
    stub._kv_tier_drop_locked = NodeDaemon._kv_tier_drop_locked.__get__(stub)

    def put(d):
        stub._last_kv_tier_sweep = -1e9  # defeat the 1s sweep throttle
        assert asyncio.run(
            NodeDaemon.d_kv_tier_put(stub, {"digest": d, "desc": {"d": d}}, None)
        )

    def get(d):
        return asyncio.run(NodeDaemon.d_kv_tier_get(stub, {"digest": d}, None))

    old_cap = GLOBAL_CONFIG.kv_tier_max_entries
    GLOBAL_CONFIG.kv_tier_max_entries = 3
    try:
        for d in ("hot", "cold1", "cold2"):
            put(d)
        for _ in range(4):  # the shared prefix keeps getting faulted in
            assert get("hot") == {"d": "hot"}
        # two colder NEWER entries arrive over cap: the zero-hit ones go
        # (oldest-recency first), the hot OLDEST entry survives both
        put("new1")
        assert set(stub._kv_tier) == {"hot", "cold2", "new1"}
        put("new2")
        assert set(stub._kv_tier) == {"hot", "new1", "new2"}
        # a re-put of a live digest counts as a use too
        put("new1")
        assert stub._kv_tier["new1"]["hits"] == 1
        # TTL still dominates popularity: an expired hot entry drops
        stub._kv_tier["hot"]["expiry"] = -1.0
        stub._last_kv_tier_sweep = -1e9
        NodeDaemon._kv_tier_sweep(stub)
        assert "hot" not in stub._kv_tier
        assert get("hot") is None
    finally:
        GLOBAL_CONFIG.kv_tier_max_entries = old_cap


def test_tier_fetch_chaos_modes_hit_the_integrity_gate():
    import numpy as np

    from ray_tpu.inference import kv_transfer

    kv = np.ones((2, 2, 1, 8, 2, 16), dtype=np.float32)
    (d1,) = _digests([4] * 8)
    desc = kv_transfer.tier_publish(d1, kv, 8)
    assert desc is not None

    # corrupt_block: the digest-before-attach gate MUST refuse it
    kv_transfer.testing_tier_plan = KvTierFaultPlan("corrupt_block:1.0", 5)
    with pytest.raises(kv_transfer.KvTransferError, match="digest"):
        kv_transfer.tier_fetch(desc)

    # missing_block: fails fast, entry untouched
    kv_transfer.testing_tier_plan = KvTierFaultPlan("missing_block:1.0", 5)
    with pytest.raises(kv_transfer.KvTransferError, match="missing"):
        kv_transfer.tier_fetch(desc)
    assert d1.hex() in kv_transfer.tier_list()

    # stale_advert: the entry is deleted under the reader, the pull
    # falls through immediately (no source, no timeout)
    kv_transfer.testing_tier_plan = KvTierFaultPlan("stale_advert:1.0", 5)
    with pytest.raises(kv_transfer.KvTransferError):
        kv_transfer.tier_fetch(desc)
    assert d1.hex() not in kv_transfer.tier_list()

    # plan exhausted (cap 1 per rule): the same descriptor now fetches
    # clean — chaos injects faults, it doesn't poison state
    desc2 = kv_transfer.tier_publish(d1, kv, 8)
    f = kv_transfer.tier_fetch(desc2)
    f.close()


# ---------------------------------------------------------------------------
# unit: router tier directory — retraction, TTL, chain matching


class _FakeHandle:
    def __init__(self, aid):
        self.actor_id = aid


def _routing_set(entries, stamp):
    """[(handle, adverts-dict)] -> controller routing_set triples."""
    return [
        (h, (), {"stats": {"prefix_digest": [], "kv_tier": adv},
                 "age_s": 0.0, "stamp": stamp})
        for h, adv in entries
    ]


def test_router_tier_retraction_and_dead_holder_ttl():
    from ray_tpu.core.config import GLOBAL_CONFIG
    from ray_tpu.observability.rpc_metrics import KV_TIER_RETRACTIONS
    from ray_tpu.serve.router import Router

    r = Router(None, "t")
    a = _FakeHandle("actor-a")
    d1, d2 = _digests([7] * 8 + [8] * 8)
    desc = {"block_size": 8}
    before = KV_TIER_RETRACTIONS._values.get((), 0.0)

    r._apply(_routing_set([(a, {d1.hex(): desc, d2.hex(): desc})], 1))
    assert set(r._tier_dir) == {d1.hex(), d2.hex()}

    # live holder drops d2 from its advert set -> ONE-HOP retraction
    r._apply(_routing_set([(a, {d1.hex(): desc})], 2))
    assert set(r._tier_dir) == {d1.hex()}
    assert KV_TIER_RETRACTIONS._values.get((), 0.0) - before == 1

    # the holder DIES (gone from the routing set): death is NOT
    # retraction — the daemon still owns the bytes, the entry stays
    # for the warm replacement...
    r._apply([])
    assert set(r._tier_dir) == {d1.hex()}
    assert KV_TIER_RETRACTIONS._values.get((), 0.0) - before == 1

    # ...but not forever: the dead-holder TTL bounds it
    old_ttl = GLOBAL_CONFIG.kv_tier_advert_ttl_s
    GLOBAL_CONFIG.kv_tier_advert_ttl_s = 0.0
    try:
        r._apply([])
        assert not r._tier_dir
    finally:
        GLOBAL_CONFIG.kv_tier_advert_ttl_s = old_ttl


def test_router_tier_attach_matches_consecutive_chain():
    from ray_tpu.serve.router import Router

    r = Router(None, "t")
    a = _FakeHandle("actor-a")
    prompt = SHARED + [77]  # 25 tokens: 3 full blocks + tail
    d1, d2, d3 = _digests(prompt)
    desc = {"block_size": 8}

    # nothing advertised -> no spec (and short prompts never match)
    assert r._tier_attach(prompt) is None

    r._apply(_routing_set([(a, {d1.hex(): desc, d3.hex(): desc})], 1))
    # d2 missing: the chain stops at the first gap — d3 is unreachable
    spec = r._tier_attach(prompt)
    assert spec["tokens"] == 8 and [b[0] for b in spec["blocks"]] == [d1.hex()]

    r._apply(_routing_set([(a, {d.hex(): desc for d in (d1, d2, d3)})], 2))
    spec = r._tier_attach(prompt)
    assert spec["tokens"] == 24
    assert [b[0] for b in spec["blocks"]] == [d1.hex(), d2.hex(), d3.hex()]
    # a prompt inside one block has no full-block prefix to attach
    assert r._tier_attach(prompt[:8]) is None


# ---------------------------------------------------------------------------
# cluster-free: engine write-back -> cross-server fault-in


def test_tier_fault_in_across_servers_byte_exact(cfg, params):
    """Engine A's prefill write-back lands in the (local) tier; server B
    faults it in from a router-built spec and produces the byte-exact
    sequence with the prefix provably warm (KV_TIER_HITS + radix hits).
    Then the armed fallback ladder: every fetch fails, the stream is
    STILL byte-exact, and the fallback is counted. Finally corrupt_block
    chaos: the digest-before-attach gate refuses the tampered payload —
    a corrupt tier can cost warmth, never correctness."""
    from ray_tpu.inference import kv_transfer
    from ray_tpu.inference.serve_llm import LLMServer
    from ray_tpu.observability.rpc_metrics import (
        KV_TIER_FALLBACKS, KV_TIER_HITS, KV_TIER_PUBLISHES,
    )

    prompt = SHARED + [77]
    ref = InferenceEngine(cfg, params, _ec(kv_tier_enabled=False)).start()
    try:
        expected = list(
            ref.generate(prompt, max_new_tokens=6, temperature=0.7, seed=3)
        )
    finally:
        ref.stop()

    pubs_before = KV_TIER_PUBLISHES._values.get(("prefill",), 0.0)
    a = InferenceEngine(cfg, params, _ec()).start()
    try:
        out_a = list(
            a.generate(prompt, max_new_tokens=6, temperature=0.7, seed=3)
        )
        assert out_a == expected
        # write-backs publish on a background thread now (REVIEW: the
        # daemon RPC must never stall the step thread) — flush turns
        # the deferral into a happens-before for the advert asserts
        assert a.flush_tier_writebacks()
        adverts = a.routing_stats()["kv_tier"]
        chain = _digests(prompt)
        assert all(d.hex() in adverts for d in chain), list(adverts)
        assert KV_TIER_PUBLISHES._values.get(("prefill",), 0.0) > pubs_before
        spec = {
            "blocks": [[d.hex(), adverts[d.hex()]] for d in chain],
            "tokens": 24,
        }
    finally:
        a.stop()

    hits_before = KV_TIER_HITS._values.get((), 0.0)
    b = LLMServer(cfg, _ec(), params=params, export_metrics=False)
    try:
        out_b = _collect(b.generate({
            "prompt": prompt, "max_new_tokens": 6,
            "temperature": 0.7, "seed": 3, "kv_tier": dict(spec),
        }))
        assert out_b == expected
        assert KV_TIER_HITS._values.get((), 0.0) - hits_before >= 3
        assert b.engine.blocks.prefix_tokens_saved_total >= 24
    finally:
        b.engine.stop()

    # armed ladder: missing_block on EVERY fetch -> counted fallback,
    # plain prefill, same bytes
    fb_before = sum(KV_TIER_FALLBACKS._values.values())
    c = LLMServer(cfg, _ec(), params=params, export_metrics=False)
    try:
        c.testing_arm_kv_tier_chaos("missing_block:1.0:0:99", 13)
        out_c = _collect(c.generate({
            "prompt": prompt, "max_new_tokens": 6,
            "temperature": 0.7, "seed": 3, "kv_tier": dict(spec),
        }))
        assert out_c == expected
        assert sum(KV_TIER_FALLBACKS._values.values()) > fb_before
        assert c.engine.blocks.prefix_tokens_saved_total == 0
    finally:
        kv_transfer.testing_tier_plan = None
        c.engine.stop()

    # corrupt_block chaos between publish and fault-in: the
    # digest-before-attach gate refuses the tampered payload, the
    # fallback is counted, and the stream is byte-exact via plain
    # prefill (same spec, same expected bytes)
    fb_before = sum(KV_TIER_FALLBACKS._values.values())
    d = LLMServer(cfg, _ec(), params=params, export_metrics=False)
    try:
        d.testing_arm_kv_tier_chaos("corrupt_block:1.0:0:99", 17)
        out_d = _collect(d.generate({
            "prompt": prompt, "max_new_tokens": 6,
            "temperature": 0.7, "seed": 3, "kv_tier": dict(spec),
        }))
        assert out_d == expected
        assert sum(KV_TIER_FALLBACKS._values.values()) > fb_before
        assert d.engine.blocks.prefix_tokens_saved_total == 0
    finally:
        kv_transfer.testing_tier_plan = None
        d.engine.stop()


# ---------------------------------------------------------------------------
# cluster-free: live decode migration (drain flushes prompt+generated)


def test_drain_migration_flushes_full_kv_and_survivor_resumes(cfg, params):
    """begin_drain(migrate=True) mid-decode: the in-flight request fails
    with the resumable migration marker, its FULL written KV — prompt
    AND generated — is tier-resident, and a survivor resumes the stream
    byte-exact from tier fault-in with the generated prefix warm (the
    state a failover used to re-prefill via replay)."""
    from ray_tpu.inference.kv_transfer import KV_MIGRATION_MARKER
    from ray_tpu.inference.serve_llm import LLMServer
    from ray_tpu.observability.rpc_metrics import KV_TIER_PUBLISHES
    from ray_tpu.util.chaos import ReplicaFaultPlan

    max_new = 20
    ref = InferenceEngine(cfg, params, _ec(kv_tier_enabled=False)).start()
    try:
        expected = list(ref.generate(
            SHARED, max_new_tokens=max_new, temperature=0.7, seed=11
        ))
    finally:
        ref.stop()

    dec_before = KV_TIER_PUBLISHES._values.get(("decode",), 0.0)
    a = InferenceEngine(cfg, params, _ec()).start()
    delivered = []
    try:
        rid = a.submit(
            SHARED, max_new_tokens=max_new, temperature=0.7, seed=11
        )
        it = a.tokens(rid, timeout=120)
        # throttle decode (one surgical stall per step) so the drain
        # deterministically lands mid-stream with >= 9 generated tokens
        # — past the 32-token boundary, so a GENERATED block is among
        # the migrated flush, not just the prompt's
        delivered.append(next(it))
        a.testing_fault_plan = ReplicaFaultPlan("stall:1.0:0.25:9999", 1)
        try:
            for t in it:
                delivered.append(t)
                if len(delivered) == 9:
                    a.begin_drain(migrate=True)
        except Exception as e:  # noqa: BLE001
            assert KV_MIGRATION_MARKER in str(e), e
        else:
            pytest.fail("drain-migration never interrupted the stream")
        d = len(delivered)
        assert 9 <= d < max_new
        assert delivered == expected[:d]
        # prompt+generated full blocks are all tier-resident
        extended = SHARED + delivered
        assert a.flush_tier_writebacks()
        adverts = a.routing_stats()["kv_tier"]
        chain = _digests(extended[: len(extended) - 1])
        assert len(chain) >= 4  # at least one generated-token block
        assert all(dg.hex() in adverts for dg in chain)
        # the generated block was flushed at its decode boundary —
        # already tier-resident BEFORE the drain even ran (a SIGKILL at
        # any point would have been just as recoverable)
        assert KV_TIER_PUBLISHES._values.get(("decode",), 0.0) > dec_before
    finally:
        a.testing_fault_plan = None
        a.stop()

    # survivor: resume exactly as the router would — extended prompt,
    # resume_from=d, tier spec for the extended chain
    b = LLMServer(cfg, _ec(), params=params, export_metrics=False)
    try:
        spec = {
            "blocks": [[dg.hex(), adverts[dg.hex()]] for dg in chain],
            "tokens": len(chain) * 8,
        }
        out = _collect(b.generate({
            "prompt": extended, "max_new_tokens": max_new,
            "temperature": 0.7, "seed": 11, "resume_from": d,
            "kv_tier": spec, "request_id": "mig-resume",
        }))
        assert [tok for _, tok in out] == expected[d:]
        assert [seq for seq, _ in out] == list(range(d, max_new))
        assert b.engine.blocks.prefix_tokens_saved_total >= len(chain) * 8 - 8
    finally:
        b.engine.stop()


def test_migrate_mid_prefill_publishes_only_written_blocks(cfg, params):
    """REVIEW (high): blocks are allocated for the WHOLE prompt at
    admission but chunked prefill writes KV incrementally — a drain
    migration landing mid-prefill must flush only positions that were
    actually prefilled, or it adverts never-written device blocks under
    the VALID chain digest of the real tokens and poisons every future
    fault-in of that prefix (the CRC gate covers transport, not
    content)."""
    from ray_tpu.inference.kv_transfer import KV_MIGRATION_MARKER

    prompt = SHARED + [41, 42, 43, 44, 45, 46, 47, 48]  # 32 = 4 blocks
    eng = InferenceEngine(cfg, params, _ec(prefill_buckets=(8,)))
    try:
        rid = eng.submit(prompt, max_new_tokens=4, temperature=0.0)
        # drive ONE step by hand (no step loop running): exactly one
        # 8-token prefill chunk lands -> prefill_pos=8, prefill NOT done
        assert eng.step()
        eng._migrate_on_drain = True
        eng._migrate_inflight()
        adverts = eng.routing_stats()["kv_tier"]
        chain = _digests(prompt)
        # only the chunk that was truly written is tier-resident; the
        # allocated-but-unwritten blocks 2..4 must NOT be published
        assert chain[0].hex() in adverts, list(adverts)
        assert all(dg.hex() not in adverts for dg in chain[1:]), list(adverts)
        with pytest.raises(Exception, match=KV_MIGRATION_MARKER):
            list(eng.tokens(rid, timeout=10))
    finally:
        eng.stop()


def test_tier_namespace_scopes_models(cfg, params):
    """REVIEW (medium): the chain digest names TOKENS and the daemon
    registry is node-global — without model-identity scoping, one model
    can serve another's KV (same architecture, different weights passes
    every shape/dtype gate). Namespaces must be deterministic across
    replicas of one deployment, disjoint across weights, enforced at
    recovery adoption AND at the fault-in consumer."""
    import numpy as np

    from ray_tpu.inference import kv_transfer
    from ray_tpu.inference.serve_llm import LLMServer
    from ray_tpu.observability.rpc_metrics import KV_TIER_FALLBACKS

    params2 = init_params(cfg, jax.random.PRNGKey(1))
    a = InferenceEngine(cfg, params, _ec())
    b = InferenceEngine(cfg, params2, _ec())
    same = InferenceEngine(cfg, params, _ec())
    assert a._tier_ns and a._tier_ns == same._tier_ns
    assert a._tier_ns != b._tier_ns

    # node-global registry holds both models' entries for the SAME
    # token chain under disjoint keys; filtered views are disjoint
    kv = np.ones((2, 2, 1, 8, 2, 16), dtype=np.float32)
    (d1,) = _digests([4] * 8)
    da = kv_transfer.tier_publish(d1, kv, 8, ns=a._tier_ns)
    db = kv_transfer.tier_publish(d1, kv, 8, ns=b._tier_ns)
    assert da["tier_ns"] == a._tier_ns and db["tier_ns"] == b._tier_ns
    raw = kv_transfer.tier_list()
    assert f"{a._tier_ns}:{d1.hex()}" in raw
    assert f"{b._tier_ns}:{d1.hex()}" in raw
    assert d1.hex() in kv_transfer.tier_list(ns=a._tier_ns)
    assert d1.hex() in kv_transfer.tier_list(ns=b._tier_ns)
    assert not kv_transfer.tier_list(ns="")

    # warm-restart recovery adopts ONLY its own namespace's entries
    a._tier_recover()
    assert a._tier_adverts[d1.hex()]["tier_ns"] == a._tier_ns
    assert all(v["tier_ns"] == a._tier_ns for v in a._tier_adverts.values())

    # fault-in consumer refuses a foreign-namespace descriptor outright
    # (counted "namespace" rung) and stays byte-exact on plain prefill
    ref = InferenceEngine(cfg, params, _ec(kv_tier_enabled=False)).start()
    try:
        expected = list(ref.generate(
            SHARED + [77], max_new_tokens=4, temperature=0.7, seed=3
        ))
    finally:
        ref.stop()
    srv = LLMServer(cfg, _ec(), params=params, export_metrics=False)
    try:
        fb_before = KV_TIER_FALLBACKS._values.get(("namespace",), 0.0)
        out = _collect(srv.generate({
            "prompt": SHARED + [77], "max_new_tokens": 4,
            "temperature": 0.7, "seed": 3,
            "kv_tier": {"blocks": [[d1.hex(), db]], "tokens": 8},
        }))
        assert out == expected
        assert (
            KV_TIER_FALLBACKS._values.get(("namespace",), 0.0) - fb_before
            == 1
        )
        assert srv.engine.blocks.prefix_tokens_saved_total == 0
    finally:
        srv.engine.stop()


def test_covered_but_failed_fault_in_books_replay_shortfall(cfg, params):
    """REVIEW: the router books replayed=0 whenever the attached chain
    COVERS the resume — but the fallback outcome is only known at the
    replica. A covered chain whose fault-in fails must book the
    delivered-region shortfall into the replay counter from the replica
    side, or resume accounting undercounts real replay work."""
    from ray_tpu.inference import kv_transfer
    from ray_tpu.inference.serve_llm import LLMServer
    from ray_tpu.observability.rpc_metrics import (
        STREAM_RESUME_REPLAY_TOKENS,
    )

    max_new, seq = 20, 9
    ref = InferenceEngine(cfg, params, _ec(kv_tier_enabled=False)).start()
    try:
        expected = list(ref.generate(
            SHARED, max_new_tokens=max_new, temperature=0.7, seed=11
        ))
    finally:
        ref.stop()
    extended = SHARED + expected[:seq]  # 33 tokens: the resume prompt

    # a real holder publishes the full chain (prefill write-back)
    a = InferenceEngine(cfg, params, _ec()).start()
    try:
        list(a.generate(extended, max_new_tokens=1, temperature=0.7, seed=2))
        assert a.flush_tier_writebacks()
        adverts = a.routing_stats()["kv_tier"]
        chain = _digests(extended)
        assert all(dg.hex() in adverts for dg in chain)
        spec = {
            "blocks": [[dg.hex(), adverts[dg.hex()]] for dg in chain],
            "tokens": len(chain) * 8,
        }
    finally:
        a.stop()
    # the spec COVERS the stream (router would book replayed=0):
    # 32 >= 33 - 8
    assert spec["tokens"] >= len(extended) - 8

    b = LLMServer(cfg, _ec(), params=params, export_metrics=False)
    try:
        b.testing_arm_kv_tier_chaos("missing_block:1.0:0:99", 13)
        before = STREAM_RESUME_REPLAY_TOKENS._values.get((), 0.0)
        out = _collect(b.generate({
            "prompt": extended, "max_new_tokens": max_new,
            "temperature": 0.7, "seed": 11, "resume_from": seq,
            "kv_tier": dict(spec), "request_id": "rs-shortfall",
        }))
        # byte-exact on the plain-replay rung regardless
        assert [tok for _, tok in out] == expected[seq:]
        # committed=0, so the shortfall is the delivered-region share
        # the router assumed warm: tokens - (P - seq) = 32 - 24 = 8
        assert (
            STREAM_RESUME_REPLAY_TOKENS._values.get((), 0.0) - before == 8
        )
    finally:
        kv_transfer.testing_tier_plan = None
        b.engine.stop()

