"""Lineage reconstruction: lost objects are rebuilt by resubmitting the
producing task (reference ``object_recovery_manager.h:90``,
``task_manager.h:273`` ResubmitTask).

Suite-time note (ISSUE 14): one MODULE-scoped head cluster instead of a
full cluster per test (was ~77s for 5 tests, each paying head spawn +
driver init + teardown). Every test still gets its own SACRIFICIAL node
carrying a test-unique pin resource, so killing it provably loses that
test's objects — leftover replacement nodes from earlier tests can never
host a later test's pinned producer."""

import itertools
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

from conftest import wait_for_node_resource

_pin_ids = itertools.count()


@pytest.fixture(scope="module")
def lineage_cluster():
    cluster = Cluster(num_cpus=2)
    time.sleep(0.5)
    ray_tpu.init(address=cluster.address)
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


@pytest.fixture
def pin(lineage_cluster):
    """(cluster, pin_resource_name, node): a fresh sacrificial node whose
    pin resource no other (leftover) node carries."""
    name = f"pin{next(_pin_ids)}"
    node = lineage_cluster.add_node(num_cpus=2, resources={name: 2})
    nid = wait_for_node_resource(name)
    return lineage_cluster, name, node, nid


def test_get_recovers_lost_object(pin):
    """Produce a big (shm) object on node B, kill B, get() — the owner
    resubmits the producing task on a replacement node."""
    cluster, res, n2, nid = pin

    @ray_tpu.remote(resources={res: 1}, num_cpus=0)
    def produce():
        return np.ones(1 << 20, dtype=np.uint8)  # 1 MiB -> shm path

    ref = produce.remote()
    # wait WITHOUT fetching: the only shm copy must stay on node B
    ready, _ = ray_tpu.wait([ref], timeout=120, fetch_local=False)
    assert ready
    cluster.remove_node(n2)
    cluster.add_node(num_cpus=2, resources={res: 2})
    wait_for_node_resource(res, exclude={nid})
    out = ray_tpu.get(ref, timeout=120)  # triggers reconstruction
    assert out.sum() == 1 << 20


def test_borrower_task_recovers_lost_dependency(pin):
    """A task consuming a lost ref triggers owner-side reconstruction
    through the borrower fetch path (w_recover_object)."""
    cluster, res, n2, nid = pin

    @ray_tpu.remote(resources={res: 1}, num_cpus=0)
    def produce():
        return np.full(1 << 20, 7, dtype=np.uint8)

    @ray_tpu.remote(num_cpus=1)
    def consume(arr):
        return int(arr[0]) + int(arr[-1])

    ref = produce.remote()
    ready, _ = ray_tpu.wait([ref], timeout=120, fetch_local=False)
    assert ready
    cluster.remove_node(n2)
    cluster.add_node(num_cpus=2, resources={res: 2})
    wait_for_node_resource(res, exclude={nid})
    assert ray_tpu.get(consume.remote(ref), timeout=120) == 14


def test_inline_results_across_node_loss_and_reconstruction(pin):
    """Inline results cross the failure paths without reconstruction:
    (a) a small (inlined) result survives losing its producing node with
    retries exhausted — it lives in the OWNER's inline cache; (b) a
    large (shm) result IS reconstructed after the node dies, and its
    INLINED dependency is served from the owner cache — the dependency's
    producing task must NOT re-run (inline values are always-available
    to lineage reconstruction)."""
    import tempfile

    cluster, res, n2, nid = pin
    marker = tempfile.mktemp(prefix="raytpu-inline-dep-")
    try:

        @ray_tpu.remote(resources={res: 1}, num_cpus=0, max_retries=0)
        def small():
            return b"inline-payload" * 8  # far under the inline threshold

        @ray_tpu.remote(num_cpus=1)
        def small_dep(path):
            with open(path, "ab") as f:
                f.write(b"x")  # side-effect counter: one byte per run
            return 7

        @ray_tpu.remote(resources={res: 1}, num_cpus=0)
        def big_from(dep):
            return np.full(1 << 20, dep, dtype=np.uint8)

        inline_ref = small.remote()
        dep_ref = small_dep.remote(marker)
        big_ref = big_from.remote(dep_ref)
        ready, _ = ray_tpu.wait(
            [inline_ref, big_ref], num_returns=2, timeout=120, fetch_local=False
        )
        assert len(ready) == 2
        cluster.remove_node(n2)
        cluster.add_node(num_cpus=2, resources={res: 2})
        wait_for_node_resource(res, exclude={nid})
        # (a) inline result: max_retries=0, so only the owner's inline
        # copy can satisfy this — no reconstruction possible or needed
        assert ray_tpu.get(inline_ref, timeout=60) == b"inline-payload" * 8
        # (b) shm result: reconstructs big_from only; the inlined dep is
        # served from the owner cache
        out = ray_tpu.get(big_ref, timeout=120)
        assert out[0] == 7 and out.sum() == 7 * (1 << 20)
        with open(marker, "rb") as f:
            assert f.read() == b"x", "inlined dependency was re-executed"
    finally:
        import os as _os

        try:
            _os.unlink(marker)
        except OSError:
            pass


def test_put_object_loss_raises_object_lost(lineage_cluster):
    """put() objects have no lineage: losing every copy surfaces
    ObjectLostError instead of hanging in a recovery loop. (put() stores
    on the driver's local — head — daemon, so no pin node is needed.)"""
    from ray_tpu.core.api import _global_worker

    ref = ray_tpu.put(np.ones(1 << 20, dtype=np.uint8))
    # Simulate losing the only shm copy: delete it from the head
    # daemon's store behind the owner's back (the reference does the
    # same with internal test hooks, ``_private/test_utils.py``).
    core = _global_worker().backend
    core.io.run(
        core.daemon.call("delete_object", {"object_id": ref.id().binary()})
    )
    with pytest.raises(ray_tpu.ObjectLostError):
        ray_tpu.get(ref, timeout=60)


def test_exhausted_reconstruction_attempts_raise(pin):
    """A ref whose producing task is out of reconstruction attempts
    surfaces ObjectLostError."""
    cluster, res, n2, nid = pin
    from ray_tpu.core.config import GLOBAL_CONFIG

    old = GLOBAL_CONFIG.max_lineage_reconstructions
    GLOBAL_CONFIG.max_lineage_reconstructions = 0
    try:

        @ray_tpu.remote(resources={res: 1}, num_cpus=0)
        def produce():
            return np.ones(1 << 20, dtype=np.uint8)

        ref = produce.remote()
        ready, _ = ray_tpu.wait([ref], timeout=120, fetch_local=False)
        assert ready
        cluster.remove_node(n2)
        with pytest.raises(ray_tpu.ObjectLostError):
            ray_tpu.get(ref, timeout=60)
    finally:
        GLOBAL_CONFIG.max_lineage_reconstructions = old
