"""Llama model tests: numerics, overfit, sharded training step.

Reference test model: RLlib/Train model unit tests; here the model zoo is
first-class (no torch equivalent exists in the reference — build-new)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.models.llama import (
    LlamaConfig,
    batch_sharding,
    forward,
    init_params,
    init_sharded,
    logical_axes,
    make_train_step,
    next_token_loss,
    param_count,
)
from ray_tpu.parallel.mesh import MeshSpec, cpu_mesh_devices, make_mesh
from ray_tpu.parallel.sharding import fsdp_rules, tp_rules


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.tiny()


def test_forward_shape_and_dtype(cfg):
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = forward(cfg, params, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_param_count_matches(cfg):
    params = init_params(cfg, jax.random.PRNGKey(0))
    actual = sum(x.size for x in jax.tree_util.tree_leaves(params))
    assert actual == param_count(cfg)


def test_logical_axes_structure_matches(cfg):
    params = init_params(cfg, jax.random.PRNGKey(0))
    axes = logical_axes(cfg)
    jax.tree_util.tree_map(
        lambda p, a: None, params, axes, is_leaf=lambda x: isinstance(x, tuple)
    )  # raises on structure mismatch


def test_causality(cfg):
    """Future tokens must not affect earlier logits."""
    params = init_params(cfg, jax.random.PRNGKey(0))
    t1 = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, cfg.vocab_size)
    t2 = t1.at[0, -1].set((t1[0, -1] + 1) % cfg.vocab_size)
    l1 = forward(cfg, params, t1)
    l2 = forward(cfg, params, t2)
    np.testing.assert_allclose(l1[0, :-1], l2[0, :-1], atol=1e-5)


def test_overfit_tiny_batch(cfg):
    """Loss drops on a fixed batch — the model learns."""
    params = init_params(cfg, jax.random.PRNGKey(0))
    opt = optax.adam(1e-2)
    step = make_train_step(cfg, opt, donate=False)
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0, cfg.vocab_size)
    batch = {"tokens": tokens, "targets": tokens}
    state = (params, opt_state)
    first = None
    for _ in range(30):
        state, loss = step(state, batch)
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.5, (first, float(loss))


def test_remat_matches(cfg):
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0, cfg.vocab_size)
    l1 = next_token_loss(cfg, params, tokens, tokens, remat=False)
    l2 = next_token_loss(cfg, params, tokens, tokens, remat=True)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)


@pytest.mark.parametrize("rules_fn", [fsdp_rules, tp_rules])
def test_sharded_train_step_8dev(cfg, rules_fn):
    devices = cpu_mesh_devices(8)
    mesh = make_mesh(MeshSpec(data=2, fsdp=2, tensor=2), devices)
    rules = rules_fn()
    opt = optax.adamw(1e-3)
    params, opt_state = init_sharded(cfg, mesh, rules, jax.random.PRNGKey(0), opt)
    step = make_train_step(cfg, opt, donate=False)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (8, 16), 0, cfg.vocab_size)
    bd = jax.device_put(
        {"tokens": tokens, "targets": tokens}, batch_sharding(mesh, rules)
    )
    (p2, _), loss = step((params, opt_state), bd)
    assert np.isfinite(float(loss))
    if rules_fn is tp_rules:
        # wq sharded over embed(fsdp) and heads(tensor) → 4 distinct shards
        spec = p2["layers"][0]["wq"].sharding.spec
        assert spec[0] == "fsdp" and spec[1] == "tensor", spec


def test_sharded_matches_single_device(cfg):
    """Same step, same data: mesh result == single-device result."""
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(5), (4, 16), 0, cfg.vocab_size)
    loss_single = next_token_loss(cfg, params, tokens, tokens)

    devices = cpu_mesh_devices(8)
    mesh = make_mesh(MeshSpec(data=2, fsdp=2, tensor=2), devices)
    rules = tp_rules()
    from ray_tpu.models.llama import param_shardings

    sharded = jax.device_put(params, param_shardings(cfg, mesh, rules))
    bd = jax.device_put(tokens, batch_sharding(mesh, rules))
    loss_sharded = next_token_loss(cfg, sharded, bd, bd)
    np.testing.assert_allclose(float(loss_single), float(loss_sharded), rtol=2e-4)
