import numpy as np
import pytest

import ray_tpu
from ray_tpu.core.exceptions import ActorDiedError, TaskError


def test_task_roundtrip(ray_start_local):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(1, 2)) == 3


def test_put_get(ray_start_local):
    arr = np.ones((10, 10))
    ref = ray_tpu.put(arr)
    np.testing.assert_array_equal(ray_tpu.get(ref), arr)


def test_ref_as_arg(ray_start_local):
    @ray_tpu.remote
    def double(x):
        return x * 2

    ref = ray_tpu.put(21)
    assert ray_tpu.get(double.remote(ref)) == 42


def test_chained_tasks(ray_start_local):
    @ray_tpu.remote
    def inc(x):
        return x + 1

    ref = inc.remote(0)
    for _ in range(9):
        ref = inc.remote(ref)
    assert ray_tpu.get(ref) == 10


def test_multiple_returns(ray_start_local):
    @ray_tpu.remote(num_returns=2)
    def two():
        return 1, 2

    a, b = two.remote()
    assert ray_tpu.get(a) == 1 and ray_tpu.get(b) == 2


def test_task_error_propagates(ray_start_local):
    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom")

    @ray_tpu.remote
    def dep(x):
        return x

    ref = boom.remote()
    with pytest.raises(TaskError) as ei:
        ray_tpu.get(ref)
    assert "kaboom" in str(ei.value)
    # errors flow through dependents
    with pytest.raises(TaskError):
        ray_tpu.get(dep.remote(ref))


def test_options_override(ray_start_local):
    @ray_tpu.remote
    def f():
        return "ok"

    assert ray_tpu.get(f.options(num_cpus=2, name="custom").remote()) == "ok"


def test_wait(ray_start_local):
    @ray_tpu.remote
    def f(i):
        return i

    refs = [f.remote(i) for i in range(4)]
    ready, not_ready = ray_tpu.wait(refs, num_returns=2)
    assert len(ready) == 2 and len(not_ready) == 2


def test_actor_basic(ray_start_local):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def incr(self, by=1):
            self.n += by
            return self.n

    c = Counter.remote(10)
    assert ray_tpu.get(c.incr.remote()) == 11
    assert ray_tpu.get(c.incr.remote(5)) == 16


def test_actor_kill(ray_start_local):
    @ray_tpu.remote
    class A:
        def ping(self):
            return "pong"

    a = A.remote()
    assert ray_tpu.get(a.ping.remote()) == "pong"
    ray_tpu.kill(a)
    with pytest.raises(ActorDiedError):
        ray_tpu.get(a.ping.remote())


def test_named_actor(ray_start_local):
    @ray_tpu.remote
    class A:
        def who(self):
            return "named"

    A.options(name="singleton").remote()
    h = ray_tpu.get_actor("singleton")
    assert ray_tpu.get(h.who.remote()) == "named"
    with pytest.raises(ValueError):
        ray_tpu.get_actor("missing")


def test_get_if_exists(ray_start_local):
    @ray_tpu.remote
    class A:
        def pid(self):
            return id(self)

    a1 = A.options(name="gie", get_if_exists=True).remote()
    a2 = A.options(name="gie", get_if_exists=True).remote()
    assert ray_tpu.get(a1.pid.remote()) == ray_tpu.get(a2.pid.remote())


def test_actor_method_decorator(ray_start_local):
    @ray_tpu.remote
    class A:
        @ray_tpu.method(num_returns=2)
        def two(self):
            return 1, 2

    a = A.remote()
    r1, r2 = a.two.remote()
    assert ray_tpu.get([r1, r2]) == [1, 2]


def test_cannot_call_remote_directly(ray_start_local):
    @ray_tpu.remote
    def f():
        return 1

    with pytest.raises(TypeError):
        f()


def test_nested_refs_in_value(ray_start_local):
    inner = ray_tpu.put(5)
    outer = ray_tpu.put({"ref": inner})
    out = ray_tpu.get(outer)
    assert ray_tpu.get(out["ref"]) == 5


def test_cluster_resources(ray_start_local):
    res = ray_tpu.cluster_resources()
    assert res["CPU"] > 0


def test_streaming_actor_method_local_mode(ray_start_local):
    """Regression (round-5 advisor): streaming actor methods used to
    block forever in local mode — submit_actor_task had no streaming
    branch, so the generator's stream was never fed."""

    @ray_tpu.remote
    class Gen:
        def __init__(self):
            self.base = 100

        def stream(self, n):
            for i in range(n):
                yield self.base + i

        def boom(self):
            yield 1
            raise RuntimeError("mid-stream")

    g = Gen.remote()
    gen = g.stream.options(num_returns="streaming").remote(3)
    assert [ray_tpu.get(r) for r in gen] == [100, 101, 102]
    # mid-stream errors surface instead of hanging
    gen2 = g.boom.options(num_returns="streaming").remote()
    it = iter(gen2)
    assert ray_tpu.get(next(it)) == 1
    with pytest.raises(Exception):
        ray_tpu.get(next(it))
