"""MoE / expert parallelism (SURVEY §2.4 build-new: EP over the
``expert`` mesh axis with GSPMD-inserted all-to-alls)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.moe import init_moe_params, moe_ffn
from ray_tpu.parallel.mesh import EXPERT, MeshSpec, cpu_mesh_devices, make_mesh


def _reference_moe(params, x, top_k):
    """Per-token reference: every token processed by its top-k experts,
    unlimited capacity."""
    B, S, d = x.shape
    xt = np.asarray(x, np.float64).reshape(-1, d)
    probs = np.asarray(jax.nn.softmax(jnp.asarray(xt) @ params["router"], axis=-1))
    out = np.zeros_like(xt)
    for t in range(len(xt)):
        idx = np.argsort(-probs[t])[:top_k]
        gates = probs[t][idx] / probs[t][idx].sum()
        for g, e in zip(gates, idx):
            wg = np.asarray(params["w_gate"][e], np.float64)
            wu = np.asarray(params["w_up"][e], np.float64)
            wd = np.asarray(params["w_down"][e], np.float64)
            h = xt[t] @ wg
            silu = h / (1 + np.exp(-h))
            out[t] += g * ((silu * (xt[t] @ wu)) @ wd)
    return out.reshape(B, S, d)


def test_moe_matches_reference_when_uncapped():
    rng = jax.random.PRNGKey(0)
    params = init_moe_params(rng, dim=16, hidden=32, num_experts=4)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16), jnp.float32)
    out, aux = moe_ffn(params, x, top_k=2, capacity_factor=8.0)  # uncapped
    ref = _reference_moe(params, x, top_k=2)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-4, rtol=1e-4)
    assert float(aux["dropped_fraction"]) == 0.0
    assert float(aux["aux_loss"]) > 0.0


def test_moe_capacity_drops_overflow():
    rng = jax.random.PRNGKey(0)
    params = init_moe_params(rng, dim=8, hidden=16, num_experts=2)
    # force every token to expert 0: positive inputs x biased router
    params["router"] = jnp.zeros((8, 2)).at[:, 0].set(10.0)
    x = jnp.abs(jax.random.normal(jax.random.PRNGKey(1), (1, 16, 8))) + 0.1
    out, aux = moe_ffn(params, x, top_k=1, capacity_factor=0.5)
    # capacity = ceil(16/2*0.5) = 4 of 16 tokens kept -> 75% dropped
    assert abs(float(aux["dropped_fraction"]) - 0.75) < 1e-6
    # dropped tokens contribute zero (residual-only pass-through):
    # the LAST tokens overflowed (slots assigned in arrival order)
    np.testing.assert_allclose(np.asarray(out[0, -1]), np.zeros(8), atol=1e-6)


def test_moe_sharded_over_expert_axis():
    """Expert-sharded params on an 8-device mesh: same numerics as
    unsharded (XLA inserts the dispatch all-to-alls)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(MeshSpec(expert=4), cpu_mesh_devices(8)[:4])
    rng = jax.random.PRNGKey(0)
    params = init_moe_params(rng, dim=16, hidden=32, num_experts=8)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16), jnp.float32)
    dense_out, _ = moe_ffn(params, x, top_k=2, capacity_factor=4.0)

    shard = {
        "router": NamedSharding(mesh, P(None, None)),
        "w_gate": NamedSharding(mesh, P(EXPERT, None, None)),
        "w_up": NamedSharding(mesh, P(EXPERT, None, None)),
        "w_down": NamedSharding(mesh, P(EXPERT, None, None)),
    }
    sharded_params = {k: jax.device_put(v, shard[k]) for k, v in params.items()}
    fn = jax.jit(lambda p, x: moe_ffn(p, x, top_k=2, capacity_factor=4.0)[0])
    sharded_out = fn(sharded_params, x)
    np.testing.assert_allclose(
        np.asarray(sharded_out), np.asarray(dense_out), atol=2e-5, rtol=2e-5
    )


def test_llama_moe_train_step():
    """MoE Llama end to end on a dp×ep mesh: finite loss, expert params
    sharded, params update."""
    import optax

    from ray_tpu.models.llama import (
        LlamaConfig,
        batch_sharding,
        init_sharded,
        make_train_step,
    )
    from ray_tpu.parallel.sharding import tp_rules

    mesh = make_mesh(MeshSpec(data=2, expert=4), cpu_mesh_devices(8))
    cfg = LlamaConfig.tiny(moe_experts=4)
    rules = tp_rules()
    optimizer = optax.adamw(1e-3)
    params, opt_state = init_sharded(cfg, mesh, rules, jax.random.PRNGKey(0), optimizer)
    # expert FFN params really are sharded over the expert axis
    spec = params["layers"][0]["w_gate"].sharding.spec
    assert spec[0] == EXPERT, spec
    step = make_train_step(cfg, optimizer, mesh=mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size, jnp.int32)
    bs = batch_sharding(mesh, rules)
    batch = {"tokens": jax.device_put(tokens, bs), "targets": jax.device_put(tokens, bs)}
    before = np.asarray(params["layers"][0]["w_gate"], np.float32).copy()
    (params2, _), loss = step((params, opt_state), batch)
    assert jnp.isfinite(loss)
    after = np.asarray(params2["layers"][0]["w_gate"], np.float32)
    assert np.abs(after - before).max() > 0
