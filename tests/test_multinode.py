"""Simulated multi-node tests (Cluster fixture, reference
``cluster_utils.Cluster`` pattern): spillback scheduling, cross-node
object transfer, node failure + actor restart."""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def two_nodes():
    from ray_tpu.core.config import GLOBAL_CONFIG

    # short daemon-side infeasible park (set BEFORE Cluster() so it
    # serializes into the daemons): the park exists to give autoscalers
    # time to react, which no test in this module has — it only delays
    # test_infeasible_task_fails' deterministic verdict by 10s
    old_grace = GLOBAL_CONFIG.infeasible_lease_grace_s
    GLOBAL_CONFIG.infeasible_lease_grace_s = 2.0
    cluster = Cluster(num_cpus=1)
    n2 = cluster.add_node(num_cpus=2, resources={"special": 2})
    time.sleep(1.0)
    ray_tpu.init(address=cluster.address)
    yield cluster, n2
    GLOBAL_CONFIG.infeasible_lease_grace_s = old_grace
    ray_tpu.shutdown()
    cluster.shutdown()


def test_resource_routing(two_nodes):
    @ray_tpu.remote(resources={"special": 1})
    def f():
        return "on-special"

    assert ray_tpu.get(f.remote(), timeout=120) == "on-special"


def test_cross_node_transfer(two_nodes):
    @ray_tpu.remote(resources={"special": 1})
    def produce():
        import numpy as np

        return np.full((400, 400), 7.0)

    @ray_tpu.remote(num_cpus=1)
    def consume(a):
        return float(a.sum())

    assert ray_tpu.get(consume.remote(produce.remote()), timeout=180) == 7.0 * 400 * 400


def test_infeasible_task_fails(two_nodes):
    from ray_tpu.core.config import GLOBAL_CONFIG

    @ray_tpu.remote(resources={"nonexistent": 1})
    def f():
        return 1

    # the infeasible verdict is gated by two patience windows (daemon
    # park + client retry) meant for autoscaled clusters; shrink the
    # CLIENT-side one — it's read in this driver process at decision
    # time — so the deterministic failure arrives in ~12s, not ~40s
    old_patience = GLOBAL_CONFIG.infeasible_fail_after_s
    GLOBAL_CONFIG.infeasible_fail_after_s = 3.0
    try:
        with pytest.raises(ray_tpu.RayTpuError):
            ray_tpu.get(f.remote(), timeout=120)
    finally:
        GLOBAL_CONFIG.infeasible_fail_after_s = old_patience
