import numpy as np
import pytest

from ray_tpu.core import serialization
from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.core.ids import JobID, ObjectID, TaskID
from ray_tpu.core.object_store import (
    MemoryStore,
    ObjectStoreFull,
    ShmStore,
    StoreClient,
)


def oid(i: int) -> ObjectID:
    return ObjectID.for_put(TaskID.for_driver(JobID.from_index(1)), i)


@pytest.fixture
def store(tmp_path):
    s = ShmStore(capacity_bytes=10 * 1024 * 1024, spill_dir=str(tmp_path))
    yield s
    s.shutdown()


def test_worker_create_daemon_adopt_read(store):
    client = StoreClient()
    arr = np.arange(10000, dtype=np.float64)
    ser = serialization.serialize(arr)
    o = oid(1)
    size = client.create_and_write(o, ser)
    store.adopt(o, size)

    # another client attaches and reads zero-copy
    reader = StoreClient()
    meta = store.ensure_local(o)
    assert meta is not None
    name, sz = meta
    buf = reader.read(o, sz)
    out = serialization.deserialize_bytes(buf)
    np.testing.assert_array_equal(out, arr)
    client.close_all()
    reader.close_all()


def test_spill_and_restore(tmp_path):
    store = ShmStore(capacity_bytes=1024 * 1024, spill_dir=str(tmp_path))
    GLOBAL_CONFIG.object_spilling_threshold = 0.8
    client = StoreClient()
    objs = []
    try:
        # 5 x 300KB > 80% of 1MB -> forces spilling
        for i in range(5):
            arr = np.full(300 * 1024 // 8, i, dtype=np.float64)
            ser = serialization.serialize(arr)
            o = oid(i + 10)
            size = client.create_and_write(o, ser)
            store.adopt(o, size)
            client.release(o)
            objs.append((o, arr))
        assert store.num_spilled > 0
        # all objects still readable (restored transparently)
        for o, arr in objs:
            name, sz = store.ensure_local(o)
            reader = StoreClient()
            out = serialization.deserialize_bytes(reader.read(o, sz))
            np.testing.assert_array_equal(out, arr)
            reader.close_all()
        assert store.num_restored > 0
    finally:
        client.close_all()
        store.shutdown()


def test_store_full(tmp_path):
    store = ShmStore(capacity_bytes=1024, spill_dir=str(tmp_path))
    with pytest.raises(ObjectStoreFull):
        store.create_with_data(oid(99), memoryview(b"x" * 2048))
    store.shutdown()


def test_delete_frees_capacity(store):
    o = oid(50)
    store.create_with_data(o, memoryview(b"y" * 1000))
    assert store.used_bytes == 1000
    store.delete(o)
    assert store.used_bytes == 0
    assert store.ensure_local(o) is None


def test_transfer_read_bytes(store):
    o = oid(60)
    payload = b"z" * 5000
    store.create_with_data(o, memoryview(payload))
    assert store.read_bytes(o) == payload


def test_memory_store_wait():
    import threading

    ms = MemoryStore()
    o = oid(70)
    assert ms.wait_for(o, timeout=0.01) is None
    threading.Timer(0.05, lambda: ms.put(o, b"data")).start()
    assert ms.wait_for(o, timeout=2.0) == b"data"
