"""Fault-tolerant data plane: pull-manager chaos suite.

Covers the PR-8 acceptance gates:
  * streaming shm receive — readers NEVER see an unsealed (mid-transfer)
    object;
  * seeded data-plane chaos (chunk_drop / chunk_corrupt / chunk_stall /
    source_die_mid_transfer): corrupted chunks are detected and
    re-fetched, stalls/drops retry, transfers survive;
  * mid-transfer source death resumes from the last verified offset on a
    surviving source (one chunk lost, not the object);
  * admission control: concurrent pulls respect pull_max_inflight_bytes
    with FIFO queueing; same-object pulls coalesce onto one transfer;
  * structured failure results distinguishing "no source has it" from
    "every transfer failed", with per-source causes;
  * spilled-source serving: restore-and-serve through read_range under
    concurrent pulls, no double restore, pinned segments untouched;
  * E2E: multi-node workload with the source node SIGKILLed mid-run —
    zero wrong or missing results.
"""

import asyncio
import threading
import time
import zlib

import pytest

from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.core.ids import JobID, ObjectID, TaskID
from ray_tpu.core.object_store import ShmStore
from ray_tpu.core.pull_manager import PullManager
from ray_tpu.core.rpc import IoThread, RpcClient, RpcServer, idempotent_methods
from ray_tpu.util.chaos import DataFaultPlan


def oid(i: int) -> ObjectID:
    return ObjectID.for_put(TaskID.for_driver(JobID.from_index(7)), i)


def _counter_total(counter) -> float:
    return sum(counter._values.values())  # noqa: SLF001 — test introspection


@pytest.fixture
def io():
    t = IoThread("transfer-test-io")
    yield t
    t.stop()


@pytest.fixture(autouse=True)
def _pull_knobs():
    """Small chunks so multi-chunk behavior is cheap to exercise; reset
    every knob (and the chaos plan) afterwards."""
    old = (
        GLOBAL_CONFIG.object_transfer_chunk_bytes,
        GLOBAL_CONFIG.pull_chunk_timeout_s,
        GLOBAL_CONFIG.pull_chunk_retries,
        GLOBAL_CONFIG.pull_max_inflight_bytes,
        GLOBAL_CONFIG.testing_pull_chaos,
        GLOBAL_CONFIG.testing_pull_chaos_seed,
    )
    GLOBAL_CONFIG.object_transfer_chunk_bytes = 64 * 1024
    GLOBAL_CONFIG.pull_chunk_timeout_s = 5.0
    yield
    (
        GLOBAL_CONFIG.object_transfer_chunk_bytes,
        GLOBAL_CONFIG.pull_chunk_timeout_s,
        GLOBAL_CONFIG.pull_chunk_retries,
        GLOBAL_CONFIG.pull_max_inflight_bytes,
        GLOBAL_CONFIG.testing_pull_chaos,
        GLOBAL_CONFIG.testing_pull_chaos_seed,
    ) = old


class FakeSource:
    """A source daemon's transfer surface (object_info/fetch_chunk) over
    an in-memory object dict — no shm segment on the source side, so the
    destination's streaming writes are the only /dev/shm activity.

    Knobs: ``die_after_chunks`` aborts the connection once N chunks were
    served (every later fetch aborts too — the source is "dead");
    ``chunk_delay_s`` paces chunks so tests can observe in-flight state;
    ``lose_objects_after`` makes fetch_chunk raise KeyError after N
    chunks (the source evicted the object mid-transfer). RAW-capable by
    default (the receiver stamps ``raw: True`` and gets an out-of-band
    payload, like a real daemon); ``no_raw`` forces the legacy pickled
    tuple reply, ``no_chunk_crc`` the pre-crc raw-bytes shape."""

    def __init__(
        self,
        io: IoThread,
        objects,
        *,
        die_after_chunks=None,
        chunk_delay_s=0.0,
        lose_objects_after=None,
        no_chunk_crc=False,
        no_raw=False,
    ):
        self.io = io
        self.objects = dict(objects)
        self.die_after_chunks = die_after_chunks
        self.chunk_delay_s = chunk_delay_s
        self.lose_objects_after = lose_objects_after
        self.no_chunk_crc = no_chunk_crc
        self.no_raw = no_raw
        self.info_calls = 0
        self.served_chunks = 0

        async def _setup():
            server = RpcServer()
            server.register("object_info", self._object_info)
            server.register("fetch_chunk", self._fetch_chunk)
            port = await server.start()
            return server, port

        self.server, self.port = io.run(_setup())

    async def _object_info(self, payload, conn):
        self.info_calls += 1
        data = self.objects.get(payload["object_id"])
        if data is None:
            return None
        return {"size": len(data), "digest": zlib.crc32(data)}

    async def _fetch_chunk(self, payload, conn):
        if (
            self.die_after_chunks is not None
            and self.served_chunks >= self.die_after_chunks
        ):
            conn.abort()  # hard reset: the puller sees ConnectionLost
            raise ConnectionError("source died")
        if self.chunk_delay_s:
            await asyncio.sleep(self.chunk_delay_s)
        if (
            self.lose_objects_after is not None
            and self.served_chunks >= self.lose_objects_after
        ):
            raise KeyError("object evicted")
        data = self.objects[payload["object_id"]]
        self.served_chunks += 1
        if payload.get("raw") and not self.no_raw and not self.no_chunk_crc:
            # zero-copy send: a memoryview straight out of the source
            # object, like a real daemon's segment window
            from ray_tpu.core.rpc import RawPayload

            view = memoryview(data)[
                payload["offset"] : payload["offset"] + payload["length"]
            ]
            return RawPayload(view, meta=zlib.crc32(view))
        chunk = data[payload["offset"] : payload["offset"] + payload["length"]]
        if self.no_chunk_crc:
            return chunk  # legacy sender shape (raw bytes)
        return (chunk, zlib.crc32(chunk))

    def addr(self):
        return ("127.0.0.1", self.port)

    def stop(self):
        self.io.run(self.server.stop())


class Harness:
    """Destination store + pull manager + cached peer clients."""

    def __init__(self, io: IoThread, tmp_path):
        self.io = io
        self.store = ShmStore(
            capacity_bytes=64 * 1024 * 1024, spill_dir=str(tmp_path / "dst")
        )
        self._clients = {}
        self.pm = PullManager(self.store, self._peer)

    def _peer(self, host, port):
        key = (host, port)
        c = self._clients.get(key)
        if c is None:
            c = self._clients[key] = RpcClient(
                host, port, name=f"peer-{port}", role="noded"
            )
        return c

    def pull(self, object_id, sources, timeout=60):
        return self.io.run(
            self.pm.pull(object_id, [s.addr() if isinstance(s, FakeSource) else s for s in sources]),
            timeout=timeout,
        )

    def read(self, object_id) -> bytes:
        data = self.store.read_bytes(object_id)
        assert data is not None
        return data

    def close(self):
        async def _close():
            for c in self._clients.values():
                await c.close()

        self.io.run(_close())
        self.store.shutdown()


@pytest.fixture
def harness(io, tmp_path):
    h = Harness(io, tmp_path)
    yield h
    h.close()


def _payload(n_chunks: int, seed: int = 0) -> bytes:
    import numpy as np

    chunk = GLOBAL_CONFIG.object_transfer_chunk_bytes
    rs = np.random.RandomState(seed)
    return rs.bytes(chunk * n_chunks - 37)  # odd size: last chunk partial


# ---------------------------------------------------------------------------
# basics: streaming receive, digest carry, legacy reply shape


def test_basic_pull_and_integrity_seal(io, harness):
    o = oid(1)
    payload = _payload(4)
    src = FakeSource(io, {o.binary(): payload})
    try:
        reply = harness.pull(o, [src])
        assert reply.get("segment") and reply["size"] == len(payload)
        assert harness.read(o) == payload
        # digest recorded at seal: this node can now serve object_info
        # without recomputing
        assert harness.store.digest_of(o) == zlib.crc32(payload)
        # idempotent local re-pull answers from the store
        again = harness.pull(o, [src])
        assert again["size"] == len(payload)
    finally:
        src.stop()


def test_legacy_raw_chunk_reply_still_works(io, harness):
    o = oid(2)
    payload = _payload(3)
    src = FakeSource(io, {o.binary(): payload}, no_chunk_crc=True)
    try:
        reply = harness.pull(o, [src])
        assert reply.get("segment")
        assert harness.read(o) == payload  # whole-object digest still verified
    finally:
        src.stop()


def test_unsealed_entry_invisible_to_readers(io, harness):
    """Mid-transfer, the destination store must deny any knowledge of the
    object — a reader can never attach a partially-written segment."""
    o = oid(3)
    payload = _payload(8)
    src = FakeSource(io, {o.binary(): payload}, chunk_delay_s=0.2)
    try:
        fut = io.post(harness.pm.pull(o, [src.addr()]))  # noqa: F841
        deadline = time.monotonic() + 10
        while src.served_chunks < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert src.served_chunks < 8, "transfer finished too fast to observe"
        # in flight: invisible
        assert harness.store.ensure_local(o) is None
        assert harness.store.contains(o) is False
        assert harness.store.read_range(o, 0, 10) is None
        # completion: visible and exact
        deadline = time.monotonic() + 30
        while harness.store.ensure_local(o) is None and time.monotonic() < deadline:
            time.sleep(0.02)
        assert harness.read(o) == payload
    finally:
        src.stop()


# ---------------------------------------------------------------------------
# seeded data-plane chaos


def _chaos(spec: str, seed: int):
    GLOBAL_CONFIG.testing_pull_chaos = spec
    GLOBAL_CONFIG.testing_pull_chaos_seed = seed


def test_chunk_corrupt_detected_and_refetched(io, harness):
    """A corrupted chunk fails its crc BEFORE touching the destination
    segment and is re-fetched — the reader sees exact bytes, always."""
    from ray_tpu.observability.rpc_metrics import PULL_INTEGRITY_FAILURES

    _chaos("chunk_corrupt:0.35", 20260804)
    plan = DataFaultPlan("chunk_corrupt:0.35", 20260804)
    assert any(plan.next_fault() for _ in range(10)), "seed precondition"
    o = oid(4)
    payload = _payload(8)
    src = FakeSource(io, {o.binary(): payload})
    before = _counter_total(PULL_INTEGRITY_FAILURES)
    try:
        reply = harness.pull(o, [src])
        assert reply.get("segment")
        assert harness.read(o) == payload
        assert _counter_total(PULL_INTEGRITY_FAILURES) > before
    finally:
        src.stop()


def test_chunk_drop_and_stall_retry(io, harness):
    from ray_tpu.observability.rpc_metrics import PULL_CHUNK_RETRIES

    GLOBAL_CONFIG.pull_chunk_retries = 8  # plenty for a 0.3 drop rate
    _chaos("chunk_drop:0.2,chunk_stall:0.1:0.05", 77)
    plan = DataFaultPlan("chunk_drop:0.2,chunk_stall:0.1:0.05", 77)
    assert any(plan.next_fault() for _ in range(10)), "seed precondition"
    o = oid(5)
    payload = _payload(8)
    src = FakeSource(io, {o.binary(): payload})
    before = _counter_total(PULL_CHUNK_RETRIES)
    try:
        reply = harness.pull(o, [src])
        assert reply.get("segment")
        assert harness.read(o) == payload
        assert _counter_total(PULL_CHUNK_RETRIES) > before
    finally:
        src.stop()


def test_chaos_source_die_fails_over(io, harness):
    """The seeded source_die_mid_transfer mode kills the current source;
    with a surviving replica the pull completes exactly."""
    spec, seed = "source_die_mid_transfer:0.08", 2
    plan = DataFaultPlan(spec, seed)
    faults = [plan.next_fault() for _ in range(20)]
    idx = [i for i, f in enumerate(faults) if f]
    # precondition on this pinned seed: the first death lands before the
    # 16-chunk transfer can finish, and ≤2 deaths total (3 sources)
    assert idx and idx[0] < 12 and len(idx) <= 2, f"seed precondition: {idx}"
    _chaos(spec, seed)
    o = oid(6)
    payload = _payload(16)
    src_a = FakeSource(io, {o.binary(): payload})
    src_b = FakeSource(io, {o.binary(): payload})
    src_c = FakeSource(io, {o.binary(): payload})
    try:
        reply = harness.pull(o, [src_a, src_b, src_c])
        assert reply.get("segment"), reply
        assert harness.read(o) == payload
        # at least one source never finished the job alone
        assert src_a.served_chunks < 16
    finally:
        for s in (src_a, src_b, src_c):
            s.stop()


# ---------------------------------------------------------------------------
# resumable multi-source failover (deterministic, no chaos plan)


def test_source_death_resumes_from_verified_offset(io, harness):
    """Source A dies after 5 chunks: the transfer fails over to B and
    resumes — B serves only the REMAINING chunks, never the whole object."""
    from ray_tpu.observability.rpc_metrics import PULL_RESUMES

    GLOBAL_CONFIG.pull_chunk_retries = 0  # first transport loss → failover
    o = oid(7)
    n_chunks = 12
    payload = _payload(n_chunks)
    src_a = FakeSource(io, {o.binary(): payload}, die_after_chunks=5)
    src_b = FakeSource(io, {o.binary(): payload})
    before = _counter_total(PULL_RESUMES)
    try:
        reply = harness.pull(o, [src_a, src_b])
        assert reply.get("segment")
        assert harness.read(o) == payload
        assert src_a.served_chunks == 5
        # resumed from the last VERIFIED offset: B serves the remainder
        # (allow one chunk of slack — A's death can discard a reply it
        # already "served" from its cork buffer), never the whole object
        assert n_chunks - 5 <= src_b.served_chunks <= n_chunks - 4
        assert src_b.served_chunks < n_chunks, "restarted instead of resuming"
        assert _counter_total(PULL_RESUMES) > before
    finally:
        src_a.stop()
        src_b.stop()


def test_source_losing_object_fails_over_immediately(io, harness):
    """KeyError from the source (object freed under the transfer) is not
    a retryable chunk fault — it's an immediate failover."""
    GLOBAL_CONFIG.pull_chunk_retries = 5
    o = oid(8)
    payload = _payload(6)
    src_a = FakeSource(io, {o.binary(): payload}, lose_objects_after=2)
    src_b = FakeSource(io, {o.binary(): payload})
    try:
        reply = harness.pull(o, [src_a, src_b])
        assert reply.get("segment")
        assert harness.read(o) == payload
        assert src_a.served_chunks == 2  # no retry burned on a gone object
    finally:
        src_a.stop()
        src_b.stop()


# ---------------------------------------------------------------------------
# admission control + single-flight


def test_admission_control_bounds_inflight_bytes(io, harness):
    """N concurrent pulls queue FIFO behind pull_max_inflight_bytes: the
    admitted high-water mark never exceeds the budget, yet every pull
    completes exactly."""
    chunk = GLOBAL_CONFIG.object_transfer_chunk_bytes
    size = 4 * chunk  # ~256 KiB per object
    budget = 2 * size + chunk  # two objects in flight, not four
    GLOBAL_CONFIG.pull_max_inflight_bytes = budget
    objs = {}
    ids = []
    for i in range(4):
        o = oid(20 + i)
        import numpy as np

        payload = np.random.RandomState(i).bytes(size)
        objs[o.binary()] = payload
        ids.append((o, payload))
    src = FakeSource(io, objs, chunk_delay_s=0.02)
    try:
        async def _all():
            return await asyncio.gather(
                *[harness.pm.pull(o, [src.addr()]) for o, _ in ids]
            )

        replies = io.run(_all(), timeout=120)
        assert all(r.get("segment") for r in replies), replies
        for o, payload in ids:
            assert harness.read(o) == payload
        assert harness.pm.max_inflight_bytes_observed <= budget
        assert harness.pm._inflight_bytes == 0  # noqa: SLF001 — budget returned
    finally:
        src.stop()


def test_same_object_pulls_coalesce(io, harness):
    """Concurrent pulls of ONE object share a single transfer: the
    source sees one probe and one set of chunks."""
    from ray_tpu.observability.rpc_metrics import PULL_COALESCED

    o = oid(30)
    n_chunks = 6
    payload = _payload(n_chunks)
    src = FakeSource(io, {o.binary(): payload}, chunk_delay_s=0.03)
    before = _counter_total(PULL_COALESCED)
    try:
        async def _both():
            return await asyncio.gather(
                harness.pm.pull(o, [src.addr()]),
                harness.pm.pull(o, [src.addr()]),
                harness.pm.pull(o, [src.addr()]),
            )

        replies = io.run(_both(), timeout=60)
        assert all(r.get("segment") for r in replies)
        assert src.info_calls == 1
        assert src.served_chunks == n_chunks
        assert _counter_total(PULL_COALESCED) >= before + 2
        assert harness.read(o) == payload
    finally:
        src.stop()


# ---------------------------------------------------------------------------
# structured failure results


def test_structured_failure_no_source(io, harness):
    o = oid(40)
    src = FakeSource(io, {})  # doesn't hold the object
    try:
        reply = harness.pull(o, [src])
        assert reply["failed"] is True
        assert reply["no_source"] is True
        (cause,) = reply["causes"].values()
        assert cause == "object not found"
    finally:
        src.stop()


def test_structured_failure_all_transfers_failed(io, harness):
    """Sources exist and advertise the object, but every transfer dies:
    the failure is NOT 'no source' and carries a cause per source."""
    GLOBAL_CONFIG.pull_chunk_retries = 0
    o = oid(41)
    payload = _payload(4)
    src_a = FakeSource(io, {o.binary(): payload}, die_after_chunks=1)
    src_b = FakeSource(io, {o.binary(): payload}, die_after_chunks=2)
    try:
        reply = harness.pull(o, [src_a, src_b])
        assert reply["failed"] is True
        assert reply["no_source"] is False
        assert len(reply["causes"]) == 2
        # nothing half-written left behind
        assert harness.store.ensure_local(o) is None
        assert harness.store.used_bytes == 0
    finally:
        src_a.stop()
        src_b.stop()


def test_pull_empty_sources(io, harness):
    reply = harness.pull(oid(42), [])
    assert reply["failed"] is True and reply["no_source"] is True


def test_deadline_exhaustion_is_timeout_not_object_loss(io, harness):
    """A pull that runs out of the caller's budget must NOT be classified
    as 'no source holds it' — live sources + no budget is a timeout (the
    owner maps it to GetTimeoutError, never lineage reconstruction)."""
    o = oid(43)
    payload = _payload(4)
    src = FakeSource(io, {o.binary(): payload})
    try:
        async def _run():
            from ray_tpu.core.deadline import deadline_scope

            with deadline_scope(0.0):
                return await harness.pm.pull(o, [src.addr()])

        reply = io.run(_run())
        assert reply["failed"] is True
        assert reply["no_source"] is False
        assert reply["deadline"] is True
        assert reply["causes"], "abort reason must be recorded"
    finally:
        src.stop()


# ---------------------------------------------------------------------------
# idempotent-method classification (satellite: bulk chunk replies must
# never churn the bounded dedup reply cache)


def test_transfer_reads_classified_idempotent_for_noded():
    methods = idempotent_methods("noded")
    for m in ("object_info", "fetch_chunk", "get_object_meta", "pull_object"):
        assert m in methods, m


# ---------------------------------------------------------------------------
# spilled-source serving (satellite): restore-and-serve via read_range
# under concurrent pulls — one restore, pinned segments untouched


class StoreSource:
    """A source with a REAL ShmStore behind the daemon's transfer
    handlers (the spill/restore path under serve load)."""

    def __init__(self, io: IoThread, tmp_path, capacity=4 * 1024 * 1024):
        self.io = io
        self.store = ShmStore(capacity_bytes=capacity, spill_dir=str(tmp_path / "srcspill"))

        async def _setup():
            server = RpcServer()

            async def object_info(payload, conn):
                o = ObjectID(payload["object_id"])
                meta = self.store.ensure_local(o)
                if meta is None:
                    return None
                return {"size": meta[1], "digest": self.store.digest_of(o)}

            async def fetch_chunk(payload, conn):
                o = ObjectID(payload["object_id"])
                if payload.get("raw"):
                    from ray_tpu.core.rpc import RawPayload

                    win = self.store.read_window(
                        o, payload["offset"], payload["length"]
                    )
                    if win is None:
                        raise KeyError("not here")
                    return RawPayload(
                        win.view, meta=zlib.crc32(win.view), close=win.close
                    )
                data = self.store.read_range(o, payload["offset"], payload["length"])
                if data is None:
                    raise KeyError("not here")
                return (data, zlib.crc32(data))

            server.register("object_info", object_info)
            server.register("fetch_chunk", fetch_chunk)
            port = await server.start()
            return server, port

        self.server, self.port = io.run(_setup())

    def addr(self):
        return ("127.0.0.1", self.port)

    def stop(self):
        self.io.run(self.server.stop())
        self.store.shutdown()


def test_spilled_source_restores_once_and_spares_pinned(io, tmp_path):
    src = StoreSource(io, tmp_path, capacity=4 * 1024 * 1024)
    h1 = Harness(io, tmp_path / "d1")
    h2_store = ShmStore(capacity_bytes=64 * 1024 * 1024, spill_dir=str(tmp_path / "d2"))
    pm2 = PullManager(h2_store, h1._peer)  # share the client cache
    pinned = oid(50)
    spilled = oid(51)
    victim = oid(52)
    try:
        import numpy as np

        pinned_data = np.random.RandomState(1).bytes(1024 * 1024)
        spilled_data = np.random.RandomState(2).bytes(int(1.5 * 1024 * 1024))
        victim_data = np.random.RandomState(3).bytes(1024 * 1024)
        src.store.create_with_data(pinned, memoryview(pinned_data))
        src.store.pin(pinned)
        src.store.create_with_data(victim, memoryview(victim_data))
        src.store.create_with_data(spilled, memoryview(spilled_data))
        # force the target object out to disk
        with src.store._lock:  # noqa: SLF001 — test-only forcing
            assert src.store._spill_one()  # LRU-first unpinned = `victim`? no: oldest unpinned
        # spill until the target object is actually on disk
        while any(
            e["object_id"] == spilled.hex() and e["in_shm"]
            for e in src.store.list_entries()
        ):
            with src.store._lock:  # noqa: SLF001
                assert src.store._spill_one()
        restored_before = src.store.num_restored

        async def _both():
            return await asyncio.gather(
                h1.pm.pull(spilled, [src.addr()]),
                pm2.pull(spilled, [src.addr()]),
            )

        r1, r2 = io.run(_both(), timeout=60)
        assert r1.get("segment") and r2.get("segment")
        assert h1.read(spilled) == spilled_data
        assert h2_store.read_bytes(spilled) == spilled_data
        # exactly ONE restore served both concurrent pulls
        assert src.store.num_restored == restored_before + 1
        # the pinned object was never spilled or unlinked by the restore
        entries = {e["object_id"]: e for e in src.store.list_entries()}
        assert entries[pinned.hex()]["in_shm"] is True
        assert src.store.read_bytes(pinned) == pinned_data
    finally:
        src.stop()
        h1.close()
        h2_store.shutdown()


# ---------------------------------------------------------------------------
# E2E: multi-node workload, source node SIGKILLed mid-run — zero wrong
# or missing results (transfer failover + lineage reconstruction)


def test_e2e_source_node_killed_mid_transfer():
    import numpy as np

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    from ray_tpu.core.config import GLOBAL_CONFIG

    cluster = None
    # replacement capacity can take tens of seconds to spawn and register
    # on a loaded box; the DEFAULT 30s client-side infeasible window was a
    # load-sensitive race — retried `produce` tasks needing "src" must
    # keep waiting for the replacement node, exactly the autoscaled-
    # cluster contract this knob exists for. (Client-side knob: set on
    # the driver's GLOBAL_CONFIG, like test_drain's grace override.)
    old_infeasible = GLOBAL_CONFIG.infeasible_fail_after_s
    GLOBAL_CONFIG.infeasible_fail_after_s = 120.0
    try:
        cluster = Cluster(num_cpus=2)
        n2 = cluster.add_node(num_cpus=2, resources={"src": 8})
        time.sleep(1.0)
        ray_tpu.init(address=cluster.address)

        # num_cpus=1 (the PR 10 workaround made this 0): the root cause
        # of this test's load-flakiness was a SCHEDULING DEADLOCK —
        # after the kill every CPU could be held by consume tasks parked
        # in arg-fetch awaiting reconstruction while the reconstructed
        # produce tasks needed a CPU to run. Blocked workers now RELEASE
        # their CPU share during sync get/arg-fetch and re-acquire on
        # wake (d_worker_blocked/d_worker_unblocked), so CPU-consuming
        # producers compete fairly with their blocked consumers — this
        # test is the regression gate for that release under real node
        # death + lineage reconstruction.
        @ray_tpu.remote(max_retries=5, num_cpus=1, resources={"src": 1})
        def produce(i):
            # STAGGERED durations (0.3s..3s): a flat sleep lets the whole
            # wave finish together, so any completion-based kill trigger
            # strands EVERY output on the dying node — and each stranded
            # object costs a serial ~10s dead-source connect probe in
            # recovery, which blows the get budget. With per-task sleeps
            # dominating, "2 produced" provably means "most still
            # mid-run" on any box speed.
            time.sleep(0.3 * (i + 1))
            return np.full((512 * 1024,), float(i), dtype=np.float64)  # 4 MiB

        @ray_tpu.remote(max_retries=5, num_cpus=0.5)
        def consume(a):
            return float(a.sum())

        refs = [produce.remote(i) for i in range(10)]
        sums = [consume.remote(r) for r in refs]

        def _kill_and_replace():
            # condition-based timing (the wall-clock 1.2s sleep this
            # replaces raced box load both ways: kill before anything
            # produced = plain full reconstruction with no transfer in
            # flight, kill after everything consumed = no fault at all):
            # wait until the FIRST produce outputs exist on the source —
            # their transfers to consumers are starting right now, while
            # later (longer-sleeping) producers are provably still
            # mid-run, so the kill exercises BOTH transfer failover and
            # in-flight task retry without stranding every output
            ray_tpu.wait(list(refs), num_returns=2, timeout=60)
            cluster.remove_node(n2)  # SIGKILL the whole node group
            # replacement capacity so lineage reconstruction of lost
            # producer outputs has somewhere to run
            cluster.add_node(num_cpus=2, resources={"src": 8})

        killer = threading.Thread(target=_kill_and_replace, daemon=True)
        killer.start()
        results = ray_tpu.get(sums, timeout=150)
        killer.join(timeout=60)
        expect = [float(i) * 512 * 1024 for i in range(10)]
        assert results == expect, (results, expect)
    finally:
        GLOBAL_CONFIG.infeasible_fail_after_s = old_infeasible
        try:
            ray_tpu.shutdown()
        finally:
            if cluster is not None:
                cluster.shutdown()
