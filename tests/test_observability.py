"""Observability floor: Prometheus metrics, state API, log forwarding
(reference: ``_private/metrics_agent.py``, ``util/state/api.py:781``,
``_private/log_monitor.py:103``)."""

import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.util import state


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=2)
    yield
    ray_tpu.shutdown()


def test_metrics_render_format():
    from ray_tpu.observability.metrics import Counter, Gauge, render

    c = Counter("raytpu_test_total", "test counter", ("kind",))
    c.inc(labels={"kind": "a"})
    c.inc(2, labels={"kind": "a"})
    g = Gauge("raytpu_test_gauge", "test gauge")
    g.set(7.5)
    text = render()
    assert '# TYPE raytpu_test_total counter' in text
    assert 'raytpu_test_total{kind="a"} 3.0' in text
    assert "raytpu_test_gauge 7.5" in text


def test_daemon_metrics_endpoint(cluster):
    from ray_tpu.core.api import _global_worker

    core = _global_worker().backend
    stats = core.io.run(core.daemon.call("stats"))
    port = stats["metrics_port"]
    assert port > 0
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=30
    ).read().decode()
    assert "raytpu_object_store_used_bytes" in body
    assert "raytpu_active_leases" in body
    # healthz too
    assert (
        urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=30).read()
        == b"ok"
    )


def test_state_api_lists(cluster):
    @ray_tpu.remote
    def job(x):
        return x

    @ray_tpu.remote
    class Holder:
        def ping(self):
            return "ok"

    h = Holder.remote()
    ray_tpu.get(h.ping.remote(), timeout=60)
    ray_tpu.get([job.remote(i) for i in range(5)], timeout=120)
    import numpy as np

    ref = ray_tpu.put(np.zeros(1 << 20, dtype=np.uint8))
    time.sleep(1.0)  # task-event batch window

    nodes = state.list_nodes()
    assert len(nodes) == 1 and nodes[0]["Alive"]
    actors = state.list_actors()
    assert any(a["state"] == "ALIVE" for a in actors)
    tasks = state.list_tasks()
    assert len(tasks) >= 5
    assert state.summarize_tasks().get("FINISHED", 0) >= 5
    objs = state.list_objects()
    assert any(o["size"] >= 1 << 20 for o in objs)
    del ref


def test_logs_forwarded_to_driver(cluster, capfd):
    @ray_tpu.remote
    def chatty():
        print("HELLO-FROM-WORKER-xyzzy")
        return 1

    assert ray_tpu.get(chatty.remote(), timeout=60) == 1
    deadline = time.time() + 15
    seen = ""
    while time.time() < deadline:
        seen += capfd.readouterr().err
        if "HELLO-FROM-WORKER-xyzzy" in seen:
            break
        time.sleep(0.5)
    assert "HELLO-FROM-WORKER-xyzzy" in seen
    assert "node=" in seen  # prefixed with worker/node identity
