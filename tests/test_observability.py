"""Observability floor: Prometheus metrics, state API, log forwarding
(reference: ``_private/metrics_agent.py``, ``util/state/api.py:781``,
``_private/log_monitor.py:103``)."""

import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.util import state


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=2)
    yield
    ray_tpu.shutdown()


def test_metrics_render_format():
    from ray_tpu.observability.metrics import Counter, Gauge, render

    # NOT raytpu_-prefixed: the catalog lint walks the live registry and
    # ad-hoc test metrics must not demand README entries
    c = Counter("rtselftest_total", "test counter", ("kind",))
    c.inc(labels={"kind": "a"})
    c.inc(2, labels={"kind": "a"})
    g = Gauge("rtselftest_gauge", "test gauge")
    g.set(7.5)
    text = render()
    assert '# TYPE rtselftest_total counter' in text
    assert 'rtselftest_total{kind="a"} 3.0' in text
    assert "rtselftest_gauge 7.5" in text

    from ray_tpu.observability.metrics import Histogram

    h = Histogram("rtselftest_seconds", "test histogram", ("stage",), buckets=(0.1, 1.0))
    h.observe(0.05, labels={"stage": "a"})
    h.observe(0.5, labels={"stage": "a"})
    h.observe(5.0, labels={"stage": "a"})
    text = render()
    assert '# TYPE rtselftest_seconds histogram' in text
    assert 'rtselftest_seconds_bucket{stage="a",le="0.1"} 1' in text
    assert 'rtselftest_seconds_bucket{stage="a",le="1.0"} 2' in text
    assert 'rtselftest_seconds_bucket{stage="a",le="+Inf"} 3' in text
    assert 'rtselftest_seconds_count{stage="a"} 3' in text
    assert 'rtselftest_seconds_sum{stage="a"} 5.55' in text

    from ray_tpu.observability.metrics import inject_label

    labeled = inject_label(text, "node", "n1")
    assert 'rtselftest_total{node="n1",kind="a"} 3.0' in labeled
    assert 'rtselftest_gauge{node="n1"} 7.5' in labeled


def test_daemon_metrics_endpoint(cluster):
    from ray_tpu.core.api import _global_worker

    core = _global_worker().backend
    stats = core.io.run(core.daemon.call("stats"))
    port = stats["metrics_port"]
    assert port > 0
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=30
    ).read().decode()
    assert "raytpu_object_store_used_bytes" in body
    assert "raytpu_active_leases" in body
    # healthz too
    assert (
        urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=30).read()
        == b"ok"
    )


def test_state_api_lists(cluster):
    @ray_tpu.remote
    def job(x):
        return x

    @ray_tpu.remote
    class Holder:
        def ping(self):
            return "ok"

    h = Holder.remote()
    ray_tpu.get(h.ping.remote(), timeout=60)
    ray_tpu.get([job.remote(i) for i in range(5)], timeout=120)
    import numpy as np

    ref = ray_tpu.put(np.zeros(1 << 20, dtype=np.uint8))
    time.sleep(1.0)  # task-event batch window

    nodes = state.list_nodes()
    assert len(nodes) == 1 and nodes[0]["Alive"]
    actors = state.list_actors()
    assert any(a["state"] == "ALIVE" for a in actors)
    tasks = state.list_tasks()
    assert len(tasks) >= 5
    assert state.summarize_tasks().get("FINISHED", 0) >= 5
    objs = state.list_objects()
    assert any(o["size"] >= 1 << 20 for o in objs)
    del ref


def test_metrics_catalog_lint(cluster):
    """Every registered ``raytpu_*`` metric — in this driver's registry,
    in every node daemon's scraped registry, in the controller's, and in
    the (jax-free) engine metric definitions — must appear in the README
    "Observability" catalog. Keeps the catalog honest as counters
    accrete: add a metric, document it, or this fails naming it."""
    import os
    import re

    import ray_tpu
    from ray_tpu.util import state

    # smoke workload so lazily-registered series exist
    @ray_tpu.remote
    def touch():
        return 1

    ray_tpu.get([touch.remote() for _ in range(3)], timeout=60)

    names = set()
    from ray_tpu.observability.metrics import _METRICS

    names |= {n for n in _METRICS if n.startswith("raytpu_")}
    # engine metrics register on import, no jax needed
    from ray_tpu.inference.engine import _engine_metrics

    names |= {m.name for m in _engine_metrics().values()}
    # every node's + the controller's live registries via federation
    tel = state.cluster_telemetry()
    for text in [tel["controller"], *tel["nodes"].values()]:
        names |= set(re.findall(r"^# TYPE (raytpu_\w+)", text, re.MULTILINE))

    assert len(names) > 20, names  # the scrape actually saw the registries
    readme = open(
        os.path.join(os.path.dirname(__file__), "..", "README.md")
    ).read()
    missing = sorted(n for n in names if f"`{n}`" not in readme)
    assert not missing, (
        f"metrics missing from the README Observability catalog: {missing}"
    )


def test_sampling_off_leaves_hot_path_span_free(cluster):
    """Default config (trace_sample_rate=0): running tasks must record
    ZERO span events — no trace ids anywhere in the timeline dump."""
    from ray_tpu.core.config import GLOBAL_CONFIG
    from ray_tpu.observability import timeline

    assert GLOBAL_CONFIG.trace_sample_rate == 0.0
    timeline.clear_events()

    @ray_tpu.remote
    def noop():
        return None

    ray_tpu.get([noop.remote() for _ in range(20)], timeout=60)
    time.sleep(2.5)  # let worker chunks export
    trace = ray_tpu.timeline()
    spans = [
        e
        for e in trace
        if (e.get("args") or {}).get("trace_id") or e.get("ph") in ("s", "f")
    ]
    assert spans == []


def test_logs_forwarded_to_driver(cluster, capfd):
    @ray_tpu.remote
    def chatty():
        print("HELLO-FROM-WORKER-xyzzy")
        return 1

    assert ray_tpu.get(chatty.remote(), timeout=60) == 1
    deadline = time.time() + 15
    seen = ""
    while time.time() < deadline:
        seen += capfd.readouterr().err
        if "HELLO-FROM-WORKER-xyzzy" in seen:
            break
        time.sleep(0.5)
    assert "HELLO-FROM-WORKER-xyzzy" in seen
    assert "node=" in seen  # prefixed with worker/node identity
