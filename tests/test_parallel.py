"""TPU parallel layer tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

from ray_tpu.parallel import (
    DATA,
    FSDP,
    TENSOR,
    MeshSpec,
    cpu_mesh_devices,
    make_mesh,
)
from ray_tpu.parallel.sharding import ddp_rules, fsdp_rules, shard_params_fsdp, tp_rules


def test_mesh_spec_resolve():
    spec = MeshSpec(fsdp=-1, tensor=2).resolve(8)
    assert spec.fsdp == 4 and spec.tensor == 2
    with pytest.raises(ValueError):
        MeshSpec(fsdp=3).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(fsdp=-1, tensor=-1).resolve(8)


def test_make_mesh_cpu():
    import jax

    mesh = make_mesh(MeshSpec(fsdp=4, tensor=2), cpu_mesh_devices(8))
    assert mesh.shape[FSDP] == 4
    assert mesh.shape[TENSOR] == 2
    assert mesh.shape[DATA] == 1


def test_sharded_matmul_psum_equivalence():
    """A tensor-parallel matmul under jit matches single-device math —
    the fake-ICI collective path end to end."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    mesh = make_mesh(MeshSpec(fsdp=2, tensor=4), cpu_mesh_devices(8))
    rules = tp_rules()
    x = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    w = np.random.RandomState(1).randn(16, 32).astype(np.float32)

    xs = jax.device_put(x, NamedSharding(mesh, rules.spec(["batch", None])))
    ws = jax.device_put(w, NamedSharding(mesh, rules.spec([None, "mlp"])))

    @jax.jit
    def f(x, w):
        return x @ w

    out = f(xs, ws)
    np.testing.assert_allclose(np.asarray(out), x @ w, rtol=1e-4, atol=1e-5)


def test_shard_params_fsdp():
    import jax

    mesh = make_mesh(MeshSpec(fsdp=8), cpu_mesh_devices(8))
    params = {
        "w1": np.zeros((512, 64), np.float32),
        "tiny": np.zeros((4,), np.float32),
    }
    shardings = shard_params_fsdp(mesh, params, min_size=1024)
    spec_w1 = shardings["w1"].spec
    assert FSDP in tuple(spec_w1)
    assert tuple(shardings["tiny"].spec) == ()


def test_rules_tables():
    assert ddp_rules()["embed"] is None
    assert fsdp_rules()["embed"] == FSDP
    assert tp_rules()["mlp"] == TENSOR


def test_psum_grad_allreduce():
    """DDP-equivalent: per-device grads psum to the global grad."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = make_mesh(MeshSpec(data=8), cpu_mesh_devices(8))
    w = jnp.ones((4,), jnp.float32)
    x = np.arange(32, dtype=np.float32).reshape(8, 4)
    xs = jax.device_put(x, NamedSharding(mesh, PartitionSpec(DATA, None)))

    def loss(w, x):
        return jnp.mean((x @ w) ** 2)

    g = jax.jit(jax.grad(loss))(w, xs)  # GSPMD inserts the all-reduce
    g_ref = jax.grad(loss)(w, x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=1e-4, atol=1e-5)


def test_slice_topology_mesh_two_slice_train_step():
    """Multi-slice (DCN) path: a 2-slice mesh (data spans slices,
    fsdp/tensor inside each slice) compiles and executes a full sharded
    train step — the VERDICT-flagged untested path (SURVEY §5.8(b))."""
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.llama import (
        LlamaConfig,
        batch_sharding,
        init_sharded,
        make_train_step,
    )
    from ray_tpu.parallel.mesh import MeshSpec, slice_topology_mesh
    from ray_tpu.parallel.sharding import tp_rules

    devices = jax.devices()
    assert len(devices) >= 8, "conftest forces an 8-device CPU mesh"
    # 2 slices x (fsdp=2, tensor=2) per slice
    mesh = slice_topology_mesh(
        2, MeshSpec(data=1, fsdp=2, tensor=2), devices=devices[:8]
    )
    assert dict(zip(mesh.axis_names, mesh.devices.shape))["data"] == 2
    rules = tp_rules()
    cfg = LlamaConfig.tiny()
    optimizer = optax.adamw(1e-3)
    params, opt_state = init_sharded(
        cfg, mesh, rules, jax.random.PRNGKey(0), optimizer
    )
    step = make_train_step(cfg, optimizer, mesh=mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size, jnp.int32)
    bs = batch_sharding(mesh, rules)
    batch = {
        "tokens": jax.device_put(tokens, bs),
        "targets": jax.device_put(tokens, bs),
    }
    (params, opt_state), loss = step((params, opt_state), batch)
    loss = float(loss)
    assert loss == loss and abs(loss) < 1e6
    # params sharded across BOTH slices' devices
    wq = params["layers"][0]["wq"]
    assert len({s.device.id for s in wq.addressable_shards}) == 8
