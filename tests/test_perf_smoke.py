"""Submit/complete hot-path perf smoke test (tier-1 safe, CPU-only).

Floors are DELIBERATELY generous (~0.1-0.3× of what this box does warm
and idle): the point is to fail loudly when a future change regresses
the submit path by an order of magnitude — cached task-spec templates
dropped, RPC micro-batching disabled, inline returns detouring through
the shm store — not to flake on a noisy CI box.
"""

import os
import time

import pytest

import ray_tpu


def _rate(fn, min_time=0.5):
    fn()  # warmup
    total = 0
    start = time.perf_counter()
    while time.perf_counter() - start < min_time:
        total += fn()
    return total / (time.perf_counter() - start)


def _floored_rate(fn, floor, min_time=0.5):
    """Rate measurement that is LOAD-AWARE on failure: a single sample
    below the floor re-measures twice more and judges the median-of-3 —
    a transient box-load spike (the PR 4 full-suite flake) loses to the
    two clean samples, while a real order-of-magnitude regression fails
    all three. The healthy path stays one sample (no extra suite time)."""
    first = _rate(fn, min_time)
    if first >= floor:
        return first
    samples = sorted([first, _rate(fn, min_time), _rate(fn, min_time)])
    return samples[1]


def test_submit_hot_path_smoke():
    ray_tpu.init(num_cpus=max(4, (os.cpu_count() or 4)))
    try:

        @ray_tpu.remote
        def noop():
            return None

        # warm the pool + template/KV caches
        ray_tpu.get([noop.remote() for _ in range(20)], timeout=120)

        def tasks_async():
            ray_tpu.get([noop.remote() for _ in range(200)], timeout=120)
            return 200

        def tasks_sync():
            ray_tpu.get(noop.remote(), timeout=60)
            return 1

        async_rate = _floored_rate(tasks_async, 250)
        sync_rate = _floored_rate(tasks_sync, 25)

        # inline results: a small result is served from the in-process
        # cache — second get must not pay any RPC (sub-ms even cold-ish)
        ref = noop.remote()
        ray_tpu.get(ref, timeout=60)
        t0 = time.perf_counter()
        for _ in range(50):
            ray_tpu.get(ref, timeout=60)
        cached_get_ms = (time.perf_counter() - t0) * 1000 / 50

        # ~0.1-0.3× of warm-box numbers (tasks_async ≈ 2000-4000/s,
        # tasks_sync ≈ 200-300/s, cached get ≈ 0.01 ms on this class of
        # box): an order-of-magnitude submit-path regression trips these
        # while ambient CI load does not.
        assert async_rate >= 250, f"tasks_async collapsed: {async_rate:.0f}/s"
        assert sync_rate >= 25, f"tasks_sync collapsed: {sync_rate:.0f}/s"
        assert cached_get_ms < 5.0, (
            f"cached inline get took {cached_get_ms:.2f} ms — the owner-side "
            "inline cache is being bypassed"
        )
    finally:
        ray_tpu.shutdown()


def test_decode_step_throughput_smoke():
    """Inference-engine decode floor (cluster-free, toy config): 4
    concurrent requests decode through the batched jitted step at
    ~1500 tokens/s warm on this box — 100/s trips only an
    order-of-magnitude regression (per-token recompiles, the decode
    batch falling apart into singletons, a python hot loop in the
    step path). The SLO ledger (ISSUE 15) is ALWAYS-ON in this path —
    per-token histogram observes, lifecycle stamps, flight-recorder
    inserts — so this floor doubles as the ledger-overhead guard:
    observability can never become the regression."""
    jax = pytest.importorskip("jax")
    from ray_tpu.inference.engine import EngineConfig, InferenceEngine
    from ray_tpu.models.llama import LlamaConfig, init_params
    from ray_tpu.observability import slo

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    ec = EngineConfig(
        num_blocks=64, block_size=8, prefill_buckets=(8,),
        decode_buckets=(4,), max_decode_batch=4,
    )
    eng = InferenceEngine(cfg, params, ec).start()
    try:
        # warm pass (first steps pay dispatch caches, not compiles —
        # warmup=True compiled the buckets at init)
        for r in [eng.submit([1 + i, 2, 3], max_new_tokens=8) for i in range(4)]:
            list(eng.tokens(r, timeout=120))
        t0 = time.perf_counter()
        rids = [eng.submit([1 + i, 2, 3], max_new_tokens=32) for i in range(4)]
        total = sum(len(list(eng.tokens(r, timeout=120))) for r in rids)
        rate = total / (time.perf_counter() - t0)
        assert total == 4 * 32
        assert eng.runner.recompiles_after_warmup() == 0
        assert rate >= 100, f"decode throughput collapsed: {rate:.0f} tokens/s"
        # the ledger provably ran during the measured window (this floor
        # is its overhead gate, so it must not be silently off) and its
        # books balance exactly at quiesce
        deadline = time.monotonic() + 10
        books = eng.ledger_books()
        while time.monotonic() < deadline and not slo.books_balanced(books):
            time.sleep(0.05)
            books = eng.ledger_books()
        assert slo.books_balanced(books), books
        assert books["submitted"] == 8 and books["finished"] == 8, books
        snap = eng.slo_snapshot()
        itl = snap["histograms"]["raytpu_llm_itl_seconds"]["values"]
        assert sum(v[-1] for v in itl.values()) >= 4 * 31, "ITL ledger idle"
    finally:
        eng.stop()


def test_warm_prefix_ttft_and_hit_rate_smoke():
    """Prefix-cache perf gate (cluster-free): a prompt whose blocks are
    already cached must reach its first token FASTER than the cold
    prefill of the same prompt, and the engine must report a nonzero
    prefix hit rate. Judged on the median-of-3 re-measure pattern
    (_floored_rate's shape): the healthy path is one cold/warm pair; a
    suspicious first pair re-measures twice more and the medians decide,
    so a box-load spike loses to the two clean samples while a real
    regression (hits not taken, COW recompiling, prefill not skipped)
    fails all three."""
    jax = pytest.importorskip("jax")
    from ray_tpu.inference.engine import EngineConfig, InferenceEngine
    from ray_tpu.models.llama import LlamaConfig, init_params

    cfg = LlamaConfig.tiny(max_seq_len=512)
    params = init_params(cfg, jax.random.PRNGKey(0))
    ec = EngineConfig(
        num_blocks=72, block_size=16, prefill_buckets=(16, 512),
        decode_buckets=(1,), max_decode_batch=1, max_new_tokens_default=2,
    )
    eng = InferenceEngine(cfg, params, ec).start()
    try:
        import numpy as np

        rs = np.random.RandomState(11)
        prompts = [
            [int(x) for x in rs.randint(1, cfg.vocab_size, size=448)]
            for _ in range(3)
        ]

        def ttft(prompt):
            t0 = time.perf_counter()
            rid = eng.submit(prompt, max_new_tokens=2)
            next(eng.tokens(rid, timeout=120))
            dt = time.perf_counter() - t0
            eng.cancel(rid)
            return dt

        def pair(prompt):
            return ttft(prompt), ttft(prompt)  # cold (populates), warm (hits)

        cold, warm = pair(prompts[0])
        if warm >= cold:  # suspicious: re-measure, judge the medians
            colds, warms = [cold], [warm]
            for p in prompts[1:]:
                c, w = pair(p)
                colds.append(c)
                warms.append(w)
            cold, warm = sorted(colds)[1], sorted(warms)[1]
        assert warm < cold, (
            f"warm-prefix TTFT {warm*1e3:.1f} ms not below cold "
            f"{cold*1e3:.1f} ms — the prefix cache is not skipping prefill"
        )
        ps = eng.blocks.prefix_stats()
        assert ps["hit_rate"] > 0, ps
        assert ps["tokens_saved_total"] >= 447, ps  # full-hit minus 1 token
        assert eng.runner.recompiles_after_warmup() == 0
    finally:
        eng.stop()


def test_ingress_http_path_smoke():
    """HTTP ingress floor (bench.py's serve_http_ttft_p50_p99 /
    ingress_goodput phase, floored): 4 concurrent SSE streams through
    the full stack — urllib → aiohttp ingress (bucket + shed policy) →
    router → streaming replica → engine. Warm numbers on this box are
    ~40-150 ms TTFT p50 and hundreds of delivered tokens/s; the floors
    trip only an order-of-magnitude regression (a blocking call parked
    on the ingress event loop, the shed path running per-token, the
    stream detouring through a non-streaming path)."""
    pytest.importorskip("jax")
    import threading

    from ray_tpu import serve
    from ray_tpu.inference.engine import EngineConfig
    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.serve.ingress import IngressConfig, http_stream

    ray_tpu.init(num_cpus=max(4, (os.cpu_count() or 4)))
    try:
        ec = EngineConfig(
            num_blocks=64, block_size=8, prefill_buckets=(8, 32),
            decode_buckets=(1, 4), max_decode_batch=4,
        )
        serve.run(serve.llm_deployment(LlamaConfig.tiny(), engine=ec).bind())
        serve.run(
            serve.ingress_deployment(
                "llm", IngressConfig(target="llm"), name="ingress"
            ).bind(),
            name="ingress",
        )
        addr = serve.ingress_addresses("ingress")[0]
        list(http_stream(addr, {"prompt": [1, 2, 3], "max_new_tokens": 4}))

        def one_round():
            n, new_tokens = 4, 16
            ttfts, counts = [], []
            lock = threading.Lock()

            def consume(i):
                t0 = time.perf_counter()
                first, c = None, 0
                for _ in http_stream(
                    addr,
                    {"prompt": [1 + i, 2, 3], "max_new_tokens": new_tokens},
                    tenant=f"t{i}", connect_timeout=120.0,
                ):
                    if first is None:
                        first = time.perf_counter() - t0
                    c += 1
                with lock:
                    ttfts.append(first)
                    counts.append(c)

            t0 = time.perf_counter()
            threads = [
                threading.Thread(target=consume, args=(i,)) for i in range(n)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            wall = time.perf_counter() - t0
            assert sum(counts) == n * new_tokens, counts
            return sorted(ttfts)[len(ttfts) // 2], sum(counts) / wall

        ttft_p50, goodput = one_round()
        if ttft_p50 > 2.0 or goodput < 20.0:
            # load-aware re-judge (the _floored_rate shape): median-of-3
            rounds = sorted([(ttft_p50, goodput), one_round(), one_round()])
            ttft_p50, goodput = rounds[1]
        assert ttft_p50 < 2.0, f"ingress TTFT p50 collapsed: {ttft_p50:.2f}s"
        assert goodput >= 20.0, f"ingress goodput collapsed: {goodput:.0f} tok/s"
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


def test_chunk_receive_path_zero_copy_guard():
    """Copy-count guard for the zero-copy data plane (cluster-free): pull
    a multi-chunk object through the RAW path and assert (a) EVERY chunk
    rode the zero-copy receive — raytpu_pull_raw_chunks_total advances by
    exactly the chunk count, so a silent fallback to the pickled copy
    path fails loudly — and (b) the tracemalloc'd python-allocator peak
    during the transfer stays a small fraction of the payload: the
    destination is an mmap-backed shm window (invisible to the traced
    allocator) and the source serves memoryview windows, so any
    full-payload bytes materialization creeping back into either end
    (pickle of bulk, msgpack re-copy, whole-object heap buffer) trips
    the bound."""
    import tracemalloc
    import zlib

    from ray_tpu.core.config import GLOBAL_CONFIG
    from ray_tpu.core.ids import JobID, ObjectID, TaskID
    from ray_tpu.core.object_store import ShmStore
    from ray_tpu.core.pull_manager import PullManager
    from ray_tpu.core.rpc import IoThread, RawPayload, RpcClient, RpcServer
    from ray_tpu.observability.rpc_metrics import PULL_CHUNKS, PULL_RAW_CHUNKS

    payload_mb = 16
    chunk_bytes = 1024 * 1024
    payload = bytes(bytearray(range(256)) * (payload_mb * 4096))
    n_chunks = payload_mb  # 16 × 1 MiB
    oid = ObjectID.for_put(TaskID.for_driver(JobID.from_index(9)), 777)

    io = IoThread("copyguard-io")
    old_chunk = GLOBAL_CONFIG.object_transfer_chunk_bytes
    GLOBAL_CONFIG.object_transfer_chunk_bytes = chunk_bytes
    store = ShmStore(capacity_bytes=4 * payload_mb * 1024 * 1024)
    clients = {}

    def peer(host, port):
        key = (host, port)
        if key not in clients:
            clients[key] = RpcClient(host, port, name="copyguard", role="noded")
        return clients[key]

    async def setup():
        server = RpcServer()

        async def object_info(p, conn):
            return {"size": len(payload), "digest": zlib.crc32(payload)}

        async def fetch_chunk(p, conn):
            view = memoryview(payload)[p["offset"] : p["offset"] + p["length"]]
            assert p.get("raw"), "receiver stopped requesting RAW framing"
            return RawPayload(view, meta=zlib.crc32(view))

        server.register("object_info", object_info)
        server.register("fetch_chunk", fetch_chunk)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    pm = PullManager(store, peer)
    try:
        raw_before = sum(PULL_RAW_CHUNKS._values.values())  # noqa: SLF001
        total_before = sum(PULL_CHUNKS._values.values())  # noqa: SLF001
        tracemalloc.start()
        try:
            reply = io.run(pm.pull(oid, [("127.0.0.1", port)]), timeout=120)
            _current, peak = tracemalloc.get_traced_memory()
        finally:
            tracemalloc.stop()
        assert reply.get("segment"), reply
        assert store.read_bytes(oid) == payload  # byte-exact, digest-sealed
        raw_chunks = sum(PULL_RAW_CHUNKS._values.values()) - raw_before  # noqa: SLF001
        chunks = sum(PULL_CHUNKS._values.values()) - total_before  # noqa: SLF001
        assert chunks == n_chunks, (chunks, n_chunks)
        assert raw_chunks == n_chunks, (
            f"only {raw_chunks}/{n_chunks} chunks rode the zero-copy path"
        )
        # generous ceiling (×4 headroom over the observed ~1-2 MiB of
        # transient reader/transport buffers) yet far below the 16 MiB
        # payload: ONE full-payload bytes object would trip it
        assert peak < payload_mb * 1024 * 1024 // 2, (
            f"traced peak {peak / 1e6:.1f} MB — a full-payload copy is back "
            "in the chunk receive path"
        )
    finally:
        GLOBAL_CONFIG.object_transfer_chunk_bytes = old_chunk

        async def teardown():
            for c in clients.values():
                await c.close()
            await server.stop()

        io.run(teardown())
        store.shutdown()
        io.stop()


def test_kv_migration_raw_path_floor_and_receive_pool_reuse():
    """KV-migration tripwires (ISSUE 13), cluster-free over the REAL
    pull path: a migration-shaped payload pulled through PullManager
    must (a) ride the RAW zero-copy receive for EVERY chunk (the
    copy-count tripwire extended to the migration path), (b) clear a
    deliberately generous throughput floor — kv_migration_gbps ~0.1+
    GB/s warm on this box over loopback, floored at 0.02 so only an
    order-of-magnitude regression (per-chunk bytes copies, RAW fallback,
    digest recompute per chunk) trips it — and (c) REUSE the receive
    segment across back-to-back migrations via the daemon-side pool
    (delete with recycle_receive → allocate_receive pool hit), the
    4.4-kernel substitute for MADV_POPULATE."""
    import zlib

    from ray_tpu.core.config import GLOBAL_CONFIG
    from ray_tpu.core.ids import JobID, ObjectID, TaskID
    from ray_tpu.core.object_store import ShmStore
    from ray_tpu.core.pull_manager import PullManager
    from ray_tpu.core.rpc import IoThread, RawPayload, RpcClient, RpcServer
    from ray_tpu.observability.rpc_metrics import PULL_CHUNKS, PULL_RAW_CHUNKS

    payload_mb = 8
    chunk_bytes = 1024 * 1024
    payloads = {
        i: bytes(bytearray((i + j) & 0xFF for j in range(256)) * (payload_mb * 4096))
        for i in (1, 2)
    }
    oids = {
        i: ObjectID.for_put(TaskID.for_driver(JobID.from_index(13)), i)
        for i in (1, 2)
    }
    by_oid = {oids[i].binary(): payloads[i] for i in (1, 2)}

    io = IoThread("kvmig-io")
    old = (
        GLOBAL_CONFIG.object_transfer_chunk_bytes,
        GLOBAL_CONFIG.receive_segment_pool_bytes,
    )
    GLOBAL_CONFIG.object_transfer_chunk_bytes = chunk_bytes
    GLOBAL_CONFIG.receive_segment_pool_bytes = 64 * 1024 * 1024
    store = ShmStore(capacity_bytes=8 * payload_mb * 1024 * 1024)
    clients = {}

    def peer(host, port):
        key = (host, port)
        if key not in clients:
            clients[key] = RpcClient(host, port, name="kvmig", role="noded")
        return clients[key]

    async def setup():
        server = RpcServer()

        async def object_info(p, conn):
            data = by_oid[p["object_id"]]
            return {"size": len(data), "digest": zlib.crc32(data)}

        async def fetch_chunk(p, conn):
            data = by_oid[p["object_id"]]
            view = memoryview(data)[p["offset"] : p["offset"] + p["length"]]
            assert p.get("raw"), "migration receiver stopped requesting RAW"
            return RawPayload(view, meta=zlib.crc32(view))

        server.register("object_info", object_info)
        server.register("fetch_chunk", fetch_chunk)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    pm = PullManager(store, peer)
    try:
        raw_before = sum(PULL_RAW_CHUNKS._values.values())  # noqa: SLF001
        total_before = sum(PULL_CHUNKS._values.values())  # noqa: SLF001

        t0 = time.perf_counter()
        reply = io.run(pm.pull(oids[1], [("127.0.0.1", port)]), timeout=120)
        dt1 = time.perf_counter() - t0
        assert reply.get("segment"), reply
        assert store.read_bytes(oids[1]) == payloads[1]

        # every migrated chunk rode the zero-copy receive
        n_chunks = payload_mb
        raw = sum(PULL_RAW_CHUNKS._values.values()) - raw_before  # noqa: SLF001
        total = sum(PULL_CHUNKS._values.values()) - total_before  # noqa: SLF001
        assert total == n_chunks and raw == n_chunks, (raw, total, n_chunks)

        # the importer's delete recycles the segment into the pool …
        assert store.delete(oids[1], recycle_receive=True) is True
        assert store.stats()["recv_pool_segments"] == 1, store.stats()

        # … and the NEXT migration reuses it instead of create+zero
        t0 = time.perf_counter()
        reply = io.run(pm.pull(oids[2], [("127.0.0.1", port)]), timeout=120)
        dt2 = time.perf_counter() - t0
        assert reply.get("segment"), reply
        assert store.read_bytes(oids[2]) == payloads[2]
        assert store.stats()["recv_pool_hits"] == 1, store.stats()

        gbps = (2 * payload_mb / 1024) / (dt1 + dt2)
        if gbps < 0.02:  # load-aware re-judge (the _floored_rate shape)
            samples = [gbps]
            for _ in range(2):
                store.delete(oids[2], recycle_receive=True)
                t0 = time.perf_counter()
                io.run(pm.pull(oids[2], [("127.0.0.1", port)]), timeout=120)
                samples.append(
                    (payload_mb / 1024) / (time.perf_counter() - t0)
                )
            gbps = sorted(samples)[1]
        assert gbps >= 0.02, f"kv_migration_gbps collapsed: {gbps:.3f} GB/s"
    finally:
        (
            GLOBAL_CONFIG.object_transfer_chunk_bytes,
            GLOBAL_CONFIG.receive_segment_pool_bytes,
        ) = old

        async def teardown():
            for c in clients.values():
                await c.close()
            await server.stop()

        io.run(teardown())
        store.shutdown()
        io.stop()
