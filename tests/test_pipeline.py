"""Pipeline parallelism over the ``stage`` mesh axis (SURVEY §2.4
build-new; GPipe schedule via shard_map + ppermute)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.parallel.mesh import MeshSpec, STAGE, cpu_mesh_devices, make_mesh
from ray_tpu.parallel.pipeline import pipeline_apply, stack_stage_params


@pytest.fixture(scope="module")
def stage4_mesh():
    return make_mesh(MeshSpec(stage=4), cpu_mesh_devices(8)[:4])


def _mlp_stage(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def test_pipeline_matches_sequential(stage4_mesh):
    """4-stage pipeline over 6 microbatches == sequential composition."""
    rng = jax.random.PRNGKey(0)
    keys = jax.random.split(rng, 4)
    per_stage = [
        {"w": jax.random.normal(k, (16, 16)) * 0.5, "b": jnp.ones((16,)) * 0.01}
        for k in keys
    ]
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(jax.random.PRNGKey(1), (6, 8, 16))  # [M, mb, d]

    out = jax.jit(
        lambda p, x: pipeline_apply(stage4_mesh, _mlp_stage, p, x)
    )(stacked, x)

    expected = x
    for p in per_stage:
        expected = _mlp_stage(p, expected)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=1e-5, rtol=1e-5)


def test_pipeline_single_microbatch(stage4_mesh):
    per_stage = [{"w": jnp.eye(4) * (i + 1), "b": jnp.zeros(4)} for i in range(4)]
    stacked = stack_stage_params(per_stage)
    x = jnp.ones((1, 2, 4))
    out = pipeline_apply(stage4_mesh, _mlp_stage, stacked, x)
    expected = x
    for p in per_stage:
        expected = _mlp_stage(p, expected)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=1e-5)


def test_pipeline_differentiable(stage4_mesh):
    """Gradients flow through the scan+ppermute schedule and match the
    sequential program's gradients."""
    per_stage = [
        {"w": jax.random.normal(jax.random.PRNGKey(i), (8, 8)) * 0.3,
         "b": jnp.zeros((8,))}
        for i in range(4)
    ]
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(jax.random.PRNGKey(9), (4, 4, 8))

    def loss_pipe(p):
        return (pipeline_apply(stage4_mesh, _mlp_stage, p, x) ** 2).mean()

    def loss_seq(stages):
        y = x
        for p in stages:
            y = _mlp_stage(p, y)
        return (y ** 2).mean()

    g_pipe = jax.jit(jax.grad(loss_pipe))(stacked)
    g_seq = jax.grad(loss_seq)(per_stage)
    g_seq_stacked = stack_stage_params(g_seq)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5
        ),
        g_pipe,
        g_seq_stacked,
    )


def test_pipeline_llama_blocks(stage4_mesh):
    """Llama transformer blocks as pipeline stages: pipelined forward
    matches the plain layer loop."""
    from ray_tpu.models.llama import (
        LlamaConfig,
        _attention_block,
        _mlp_block,
        init_params,
        rope_tables,
    )

    cfg = LlamaConfig(
        vocab_size=64, dim=32, n_layers=4, n_heads=4, n_kv_heads=4,
        mlp_hidden=64, max_seq_len=16, attention_impl="xla",
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64, jnp.int32)
    cos, sin = rope_tables(cfg, 16)

    def stage_fn(layer_params, x):
        x = _attention_block(cfg, layer_params, x, cos, sin)
        x, _aux = _mlp_block(cfg, layer_params, x)
        return x

    # one layer per stage; batch 4 → 2 microbatches of 2
    stacked = stack_stage_params(params["layers"])
    x = params["embed"][tokens]  # [4, 16, 32]
    micro = x.reshape(2, 2, 16, 32)
    out = jax.jit(
        lambda p, m: pipeline_apply(stage4_mesh, stage_fn, p, m)
    )(stacked, micro).reshape(4, 16, 32)

    expected = x
    for p in params["layers"]:
        expected = stage_fn(p, expected)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=1e-4, rtol=1e-4)
