"""Placement group API + gang scheduling tests."""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util import (
    PlacementGroupSchedulingStrategy,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from ray_tpu.util.placement_group import tpu_slice_bundles


def test_pg_validation(ray_start_local):
    with pytest.raises(ValueError):
        placement_group([], strategy="PACK")
    with pytest.raises(ValueError):
        placement_group([{"CPU": 1}], strategy="DIAGONAL")
    with pytest.raises(ValueError):
        placement_group([{"CPU": -1}])


def test_tpu_slice_bundles():
    bundles = tpu_slice_bundles(4, chips_per_host=4, topology="v4-32")
    assert len(bundles) == 4
    assert bundles[0]["TPU-v4-32-head"] == 1.0
    assert all(b["TPU"] == 4.0 for b in bundles)


@pytest.fixture(scope="module")
def pg_cluster():
    from ray_tpu.core.config import GLOBAL_CONFIG

    # Shorter scheduling deadline for this module (set BEFORE Cluster()
    # so it serializes into the controller): node picking is instant when
    # capacity exists — the deadline only gates how long INFEASIBLE
    # verdicts take (test_pg_infeasible: 30s → 10s of pure waiting).
    # Worker cold-boot is NOT under this deadline (start_actor returns at
    # spawn), so feasible placements are unaffected.
    old_lease = GLOBAL_CONFIG.worker_lease_timeout_s
    GLOBAL_CONFIG.worker_lease_timeout_s = 10.0
    cluster = Cluster(num_cpus=2)
    cluster.add_node(num_cpus=2)
    time.sleep(1.0)
    ray_tpu.init(address=cluster.address)
    yield cluster
    GLOBAL_CONFIG.worker_lease_timeout_s = old_lease
    ray_tpu.shutdown()
    cluster.shutdown()


def test_pg_pack_and_schedule(pg_cluster):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK").ready(timeout=60)

    @ray_tpu.remote(num_cpus=1)
    def where():
        import os

        return os.getpid()

    strat = PlacementGroupSchedulingStrategy(pg, placement_group_bundle_index=0)
    pid = ray_tpu.get(where.options(scheduling_strategy=strat).remote(), timeout=120)
    assert pid > 0
    remove_placement_group(pg)


def test_pg_strict_spread(pg_cluster):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD").ready(timeout=60)

    @ray_tpu.remote(num_cpus=1)
    def node_of():
        return ray_tpu.get_runtime_context().get_node_id()

    strat0 = PlacementGroupSchedulingStrategy(pg, placement_group_bundle_index=0)
    strat1 = PlacementGroupSchedulingStrategy(pg, placement_group_bundle_index=1)
    n0 = ray_tpu.get(node_of.options(scheduling_strategy=strat0).remote(), timeout=120)
    n1 = ray_tpu.get(node_of.options(scheduling_strategy=strat1).remote(), timeout=120)
    assert n0 != n1  # bundles on distinct nodes
    remove_placement_group(pg)


def test_pg_infeasible(pg_cluster):
    pg = placement_group([{"CPU": 64}], strategy="STRICT_PACK")
    with pytest.raises(ray_tpu.RayTpuError):
        pg.ready(timeout=60)


def test_pg_actor_placement(pg_cluster):
    pg = placement_group([{"CPU": 1}], strategy="PACK").ready(timeout=60)

    @ray_tpu.remote(num_cpus=1)
    class Pinned:
        def ping(self):
            return "ok"

    strat = PlacementGroupSchedulingStrategy(pg, placement_group_bundle_index=0)
    a = Pinned.options(scheduling_strategy=strat).remote()
    assert ray_tpu.get(a.ping.remote(), timeout=120) == "ok"
    ray_tpu.kill(a)
    remove_placement_group(pg)


def test_pg_table(pg_cluster):
    pg = placement_group([{"CPU": 1}], strategy="SPREAD", name="mypg").ready(timeout=60)
    table = placement_group_table()
    assert pg.id.hex() in table
    assert table[pg.id.hex()]["name"] == "mypg"
    remove_placement_group(pg)


@pytest.mark.slow
def test_pg_churn_under_load(pg_cluster):
    """Create/remove many PGs while long tasks hold leased workers.

    Regression for the round-2 bench wedge: a get_pg poll reply carrying
    PENDING could clobber a concurrently-pushed CREATED in the client's
    state cache, after which wait_pg_ready never re-polled and hung until
    timeout (reference churns PGs at 838/s, ``ray_perf.py``)."""

    @ray_tpu.remote(num_cpus=0.5)
    def slow():
        time.sleep(8)
        return 1

    running = [slow.remote() for _ in range(4)]
    for i in range(50):
        pg = placement_group([{"CPU": 0.01}], strategy="PACK")
        pg.ready(timeout=30)
        remove_placement_group(pg)
    assert ray_tpu.get(running, timeout=120) == [1, 1, 1, 1]
