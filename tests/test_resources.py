import pytest

from ray_tpu.core.resources import NodeResources, ResourceSet, pg_resource_name, tpu_slice_head_resource


def test_fixed_point_no_drift():
    r = ResourceSet({"CPU": 0.1})
    total = ResourceSet({})
    for _ in range(10):
        total = total.add(r)
    assert total.get("CPU") == 1.0


def test_covers_and_subtract():
    node = ResourceSet({"CPU": 4, "TPU": 8})
    req = ResourceSet({"CPU": 1, "TPU": 4})
    assert node.covers(req)
    rem = node.subtract(req)
    assert rem.get("CPU") == 3 and rem.get("TPU") == 4
    with pytest.raises(ValueError):
        rem.subtract(ResourceSet({"TPU": 5}))


def test_node_resources_alloc_release_utilization():
    node = NodeResources(ResourceSet({"CPU": 4, "TPU": 4}))
    req = ResourceSet({"CPU": 2})
    assert node.can_fit(req)
    node.allocate(req)
    assert node.available.get("CPU") == 2
    assert node.utilization() == 0.5
    node.release(req)
    assert node.available.get("CPU") == 4
    assert node.utilization() == 0


def test_pg_shadow_resource_names():
    assert pg_resource_name("CPU", "abcd") == "CPU_group_abcd"
    assert pg_resource_name("TPU", "abcd", 2) == "TPU_group_2_abcd"
    assert tpu_slice_head_resource("v5e-8") == "TPU-v5e-8-head"
