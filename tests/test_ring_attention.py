"""Sequence-parallel attention (ring + Ulysses) on the virtual 8-device
CPU mesh — numerics vs the dense oracle, gradients, and the Llama
integration (SURVEY §5.7 north star; fake-ICI strategy per §4.3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import reference_attention
from ray_tpu.ops.ring_attention import (
    ring_attention_sharded,
    ulysses_attention_sharded,
)
from ray_tpu.parallel.mesh import MeshSpec, cpu_mesh_devices, make_mesh


def _qkv(b=2, h=8, s=64, d=16, dtype=jnp.float32, seed=0):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (
        jax.random.normal(kq, (b, h, s, d), dtype),
        jax.random.normal(kk, (b, h, s, d), dtype),
        jax.random.normal(kv, (b, h, s, d), dtype),
    )


@pytest.fixture(scope="module")
def seq8_mesh():
    return make_mesh(MeshSpec(seq=8), cpu_mesh_devices(8))


@pytest.fixture(scope="module")
def mixed_mesh():
    """dp=2 × sp=2 × tp=2: every sequence-parallel axis combined."""
    return make_mesh(MeshSpec(data=2, seq=2, tensor=2), cpu_mesh_devices(8))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_reference(seq8_mesh, causal):
    q, k, v = _qkv()
    ref = reference_attention(q, k, v, causal=causal)
    out = jax.jit(
        lambda q, k, v: ring_attention_sharded(q, k, v, seq8_mesh, causal=causal)
    )(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_ring_gradients_match(seq8_mesh):
    q, k, v = _qkv()

    def loss_ring(q, k, v):
        return (ring_attention_sharded(q, k, v, seq8_mesh, causal=True) ** 2).mean()

    def loss_ref(q, k, v):
        return (reference_attention(q, k, v, causal=True) ** 2).mean()

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(a, b, atol=3e-5, rtol=3e-5)


def test_ring_on_mixed_mesh(mixed_mesh):
    """Ring composes with data + tensor parallelism on one mesh."""
    q, k, v = _qkv()
    ref = reference_attention(q, k, v, causal=True)
    out = jax.jit(
        lambda q, k, v: ring_attention_sharded(q, k, v, mixed_mesh, causal=True)
    )(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_reference(seq8_mesh, causal):
    q, k, v = _qkv()
    ref = reference_attention(q, k, v, causal=causal)
    out = jax.jit(
        lambda q, k, v: ulysses_attention_sharded(
            q, k, v, seq8_mesh, causal=causal, impl="xla"
        )
    )(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_ulysses_gradients_match(seq8_mesh):
    q, k, v = _qkv()

    def loss_uly(q, k, v):
        return (
            ulysses_attention_sharded(q, k, v, seq8_mesh, causal=True, impl="xla") ** 2
        ).mean()

    def loss_ref(q, k, v):
        return (reference_attention(q, k, v, causal=True) ** 2).mean()

    g = jax.jit(jax.grad(loss_uly, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(a, b, atol=3e-5, rtol=3e-5)


def test_ring_gqa_kv_repeat(seq8_mesh):
    """GQA: the ring rotates unrepeated KV heads (kv_repeat) and matches
    the dense oracle fed pre-repeated K/V."""
    b, h, hkv, s, d = 2, 8, 2, 64, 16
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(kq, (b, h, s, d), jnp.float32)
    k = jax.random.normal(kk, (b, hkv, s, d), jnp.float32)
    v = jax.random.normal(kv, (b, hkv, s, d), jnp.float32)
    rep = h // hkv
    ref = reference_attention(
        q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1), causal=True
    )
    out = jax.jit(
        lambda q, k, v: ring_attention_sharded(
            q, k, v, seq8_mesh, causal=True, kv_repeat=rep
        )
    )(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_ulysses_gqa(seq8_mesh):
    """GQA Ulysses: unrepeated KV heads are exchanged when divisible by
    the seq degree, with local repeat after the all-to-all."""
    b, h, hkv, s, d = 2, 16, 8, 64, 16
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(4), 3)
    q = jax.random.normal(kq, (b, h, s, d), jnp.float32)
    k = jax.random.normal(kk, (b, hkv, s, d), jnp.float32)
    v = jax.random.normal(kv, (b, hkv, s, d), jnp.float32)
    rep = h // hkv
    ref = reference_attention(
        q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1), causal=True
    )
    out = jax.jit(
        lambda q, k, v: ulysses_attention_sharded(
            q, k, v, seq8_mesh, causal=True, impl="xla"
        )
    )(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_ulysses_rejects_indivisible_heads(seq8_mesh):
    q, k, v = _qkv(h=4)  # 4 heads, seq degree 8
    with pytest.raises(ValueError, match="divisible"):
        jax.jit(
            lambda q, k, v: ulysses_attention_sharded(q, k, v, seq8_mesh, impl="xla")
        )(q, k, v)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_llama_forward_seq_parallel_matches_dense(mixed_mesh, impl):
    """The flagship model path: seq-parallel attention inside the full
    Llama forward matches the dense-attention forward exactly."""
    from ray_tpu.models.llama import LlamaConfig, forward, init_params

    base = dict(vocab_size=128, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
                mlp_hidden=64, max_seq_len=32)
    cfg_sp = LlamaConfig(**base, attention_impl=impl)
    cfg_dense = LlamaConfig(**base, attention_impl="xla")
    params = init_params(cfg_dense, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128, jnp.int32)

    dense = forward(cfg_dense, params, tokens)
    sp = jax.jit(lambda p, t: forward(cfg_sp, p, t, mesh=mixed_mesh))(params, tokens)
    np.testing.assert_allclose(sp, dense, atol=1e-4, rtol=1e-4)


def test_llama_seq_parallel_requires_mesh():
    from ray_tpu.models.llama import LlamaConfig, forward, init_params

    cfg = LlamaConfig.tiny(attention_impl="ring")
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.zeros((1, 16), jnp.int32)
    with pytest.raises(ValueError, match="sequence-parallel"):
        forward(cfg, params, tokens)


def test_llama_train_step_seq_parallel(mixed_mesh):
    """One optimizer step with ring attention on the dp×sp×tp mesh:
    finite loss and params updated — the dryrun path as a unit test."""
    import optax

    from ray_tpu.models.llama import (
        LlamaConfig,
        batch_sharding,
        init_sharded,
        make_train_step,
    )
    from ray_tpu.parallel.sharding import tp_rules

    cfg = LlamaConfig.tiny(attention_impl="ring")
    rules = tp_rules()
    optimizer = optax.adamw(1e-3)
    params, opt_state = init_sharded(
        cfg, mixed_mesh, rules, jax.random.PRNGKey(0), optimizer
    )
    step = make_train_step(cfg, optimizer, mesh=mixed_mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size, jnp.int32)
    bs = batch_sharding(mixed_mesh, rules)
    batch = {
        "tokens": jax.device_put(tokens, bs),
        "targets": jax.device_put(tokens, bs),
    }
    before = np.asarray(params["layers"][0]["wq"], dtype=np.float32)
    (params2, _), loss = step((params, opt_state), batch)  # donates params
    assert jnp.isfinite(loss)
    after = np.asarray(params2["layers"][0]["wq"], dtype=np.float32)
    assert np.max(np.abs(after - before)) > 0
