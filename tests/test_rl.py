"""ray_tpu.rl tests: PPO on CartPole with EnvRunner actors (reference
test model: ``rllib/tuned_examples`` learning tests asserting reward
thresholds)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import PPO, PPOConfig


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_gae_matches_closed_form():
    """GAE on a 1-env, no-done rollout vs the textbook recursion."""
    T = 5
    rollout = {
        "rewards": np.ones((T, 1), np.float32),
        "values": np.zeros((T, 1), np.float32),
        "dones": np.zeros((T, 1), np.bool_),
        "last_values": np.zeros((1,), np.float32),
    }
    gamma, lam = 0.9, 0.8
    adv, ret = PPO._gae(rollout, gamma, lam)
    expected = np.zeros(T)
    last = 0.0
    for t in reversed(range(T)):
        last = 1.0 + gamma * lam * last
        expected[t] = last
    np.testing.assert_allclose(adv[:, 0], expected, rtol=1e-6)
    np.testing.assert_allclose(ret, adv)  # values are zero


def test_gae_resets_at_done():
    rollout = {
        "rewards": np.ones((3, 1), np.float32),
        "values": np.zeros((3, 1), np.float32),
        "dones": np.array([[False], [True], [False]]),
        "last_values": np.full((1,), 10.0, np.float32),
    }
    adv, _ = PPO._gae(rollout, gamma=1.0, lam=1.0)
    assert adv[1, 0] == 1.0  # episode boundary: no bootstrap through done
    assert adv[2, 0] == 11.0  # bootstraps from last_values


def test_ppo_learns_cartpole(cluster):
    """Learning test: mean episode return must clearly improve within a
    small budget (reference rllib learning-test pattern)."""
    algo = PPOConfig(
        num_env_runners=2,
        num_envs_per_runner=4,
        rollout_fragment_length=128,
        minibatch_size=256,
        seed=1,
    ).build()
    try:
        first = algo.train()["episode_return_mean"]
        last = first
        for _ in range(14):
            last = algo.train()["episode_return_mean"]
            if last >= 60.0:
                break
        assert last >= 60.0 or last >= 2.5 * max(first, 15.0), (first, last)
    finally:
        algo.stop()


def test_ppo_state_roundtrip(cluster):
    algo = PPOConfig(num_env_runners=1, num_envs_per_runner=2,
                     rollout_fragment_length=32, seed=2).build()
    try:
        algo.train()
        state = algo.get_state()
        obs = np.zeros(4, np.float32)
        action_before = algo.compute_single_action(obs)

        algo2 = PPOConfig(num_env_runners=1, num_envs_per_runner=2,
                          rollout_fragment_length=32, seed=3).build()
        try:
            algo2.set_state(state)
            assert algo2.iteration == algo.iteration
            assert algo2.compute_single_action(obs) == action_before
        finally:
            algo2.stop()
    finally:
        algo.stop()
