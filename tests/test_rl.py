"""ray_tpu.rl tests: PPO on CartPole with EnvRunner actors (reference
test model: ``rllib/tuned_examples`` learning tests asserting reward
thresholds)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import PPO, PPOConfig

from conftest import multiprocess_cpu_collectives


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_gae_matches_closed_form():
    """GAE on a 1-env, no-done rollout vs the textbook recursion."""
    T = 5
    rollout = {
        "rewards": np.ones((T, 1), np.float32),
        "values": np.zeros((T, 1), np.float32),
        "dones": np.zeros((T, 1), np.bool_),
        "last_values": np.zeros((1,), np.float32),
    }
    gamma, lam = 0.9, 0.8
    adv, ret = PPO._gae(rollout, gamma, lam)
    expected = np.zeros(T)
    last = 0.0
    for t in reversed(range(T)):
        last = 1.0 + gamma * lam * last
        expected[t] = last
    np.testing.assert_allclose(adv[:, 0], expected, rtol=1e-6)
    np.testing.assert_allclose(ret, adv)  # values are zero


def test_gae_resets_at_done():
    rollout = {
        "rewards": np.ones((3, 1), np.float32),
        "values": np.zeros((3, 1), np.float32),
        "dones": np.array([[False], [True], [False]]),
        "last_values": np.full((1,), 10.0, np.float32),
    }
    adv, _ = PPO._gae(rollout, gamma=1.0, lam=1.0)
    assert adv[1, 0] == 1.0  # episode boundary: no bootstrap through done
    assert adv[2, 0] == 11.0  # bootstraps from last_values


@pytest.mark.slow
def test_ppo_learns_cartpole(cluster):
    """Learning test: mean episode return must clearly improve within a
    small budget (reference rllib learning-test pattern)."""
    algo = PPOConfig(
        num_env_runners=2,
        num_envs_per_runner=4,
        rollout_fragment_length=128,
        minibatch_size=256,
        seed=1,
    ).build()
    try:
        first = algo.train()["episode_return_mean"]
        last = first
        for _ in range(14):
            last = algo.train()["episode_return_mean"]
            if last >= 60.0:
                break
        assert last >= 60.0 or last >= 2.5 * max(first, 15.0), (first, last)
    finally:
        algo.stop()


def test_ppo_state_roundtrip(cluster):
    algo = PPOConfig(num_env_runners=1, num_envs_per_runner=2,
                     rollout_fragment_length=32, seed=2).build()
    try:
        algo.train()
        state = algo.get_state()
        obs = np.zeros(4, np.float32)
        action_before = algo.compute_single_action(obs)

        algo2 = PPOConfig(num_env_runners=1, num_envs_per_runner=2,
                          rollout_fragment_length=32, seed=3).build()
        try:
            algo2.set_state(state)
            assert algo2.iteration == algo.iteration
            assert algo2.compute_single_action(obs) == action_before
        finally:
            algo2.stop()
    finally:
        algo.stop()


@multiprocess_cpu_collectives
def test_learner_group_matches_single_process(cluster):
    """A 2-process LearnerGroup update (one pjit program, batch sharded
    over the gang) must be numerically IDENTICAL to a single-process
    update on the whole batch (reference learner_group.py:81 DDP
    equivalence)."""
    import cloudpickle  # noqa: F401 — exercised via the group

    from ray_tpu.rl.learner_group import LearnerGroup

    def init_fn():
        import jax
        import jax.numpy as jnp

        k = jax.random.PRNGKey(0)
        w = jax.random.normal(k, (4, 1))
        return (w, jnp.zeros((4, 1)))

    def update_builder():
        import jax
        import jax.numpy as jnp

        def update(state, batch):
            w, m = state

            def loss_fn(w):
                pred = batch["x"] @ w
                return ((pred - batch["y"]) ** 2).mean()

            loss, g = jax.value_and_grad(loss_fn)(w)
            m = 0.9 * m + g
            w = w - 0.1 * m
            return (w, m), {"loss": loss}

        return update

    rng = np.random.default_rng(3)
    batch = {
        "x": rng.standard_normal((16, 4)).astype(np.float32),
        "y": rng.standard_normal((16, 1)).astype(np.float32),
    }

    group = LearnerGroup(
        num_learners=2, init_fn=init_fn, update_builder=update_builder
    )
    try:
        stats2 = [group.update(batch) for _ in range(3)]
        w2 = group.get_state()[0]
    finally:
        group.shutdown()

    single = LearnerGroup(
        num_learners=1, init_fn=init_fn, update_builder=update_builder
    )
    try:
        stats1 = [single.update(batch) for _ in range(3)]
        w1 = single.get_state()[0]
    finally:
        single.shutdown()

    for s1, s2 in zip(stats1, stats2):
        assert abs(s1["loss"] - s2["loss"]) < 1e-5, (s1, s2)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w2), rtol=1e-5)


@pytest.mark.slow
def test_impala_learns_cartpole(cluster):
    """IMPALA learning test (reference rllib learning-test pattern):
    async V-trace updates must clearly improve the mean return."""
    from ray_tpu.rl import IMPALAConfig

    algo = IMPALAConfig(
        num_env_runners=2,
        num_envs_per_runner=4,
        rollout_fragment_length=128,
        lr=1e-3,
        seed=1,
    ).build()
    try:
        first = algo.train()["episode_return_mean"]
        last = first
        for _ in range(30):
            last = algo.train()["episode_return_mean"]
            if last >= 60.0:
                break
        assert last >= 60.0 or last >= 2.5 * max(first, 15.0), (first, last)
    finally:
        algo.stop()


def test_impala_state_roundtrip(cluster):
    from ray_tpu.rl import IMPALAConfig

    algo = IMPALAConfig(
        num_env_runners=1, num_envs_per_runner=2, rollout_fragment_length=32,
        seed=2,
    ).build()
    try:
        algo.train()
        state = algo.get_state()
        obs = np.zeros(4, np.float32)
        before = algo.compute_single_action(obs)
        algo2 = IMPALAConfig(
            num_env_runners=1, num_envs_per_runner=2,
            rollout_fragment_length=32, seed=3,
        ).build()
        try:
            algo2.set_state(state)
            assert algo2.compute_single_action(obs) == before
        finally:
            algo2.stop()
    finally:
        algo.stop()


@multiprocess_cpu_collectives
def test_impala_with_learner_gang(cluster):
    """IMPALA over a 2-process LearnerGroup: the V-trace update ships to
    the gang as one pjit program (batch sharded over envs) and training
    still progresses + round-trips state."""
    from ray_tpu.rl import IMPALAConfig

    algo = IMPALAConfig(
        num_env_runners=1,
        num_envs_per_runner=4,  # divisible by the gang size
        rollout_fragment_length=32,
        rollouts_per_iteration=2,
        num_learners=2,
        seed=5,
    ).build()
    try:
        out = algo.train()
        assert out["num_env_steps_trained"] > 0
        assert "loss" in out
        obs = np.zeros(4, np.float32)
        state = algo.get_state()
        before = algo.compute_single_action(obs)
        algo.set_state(state)
        assert algo.compute_single_action(obs) == before
    finally:
        algo.stop()


def test_replay_buffer_wraps_and_samples():
    from ray_tpu.rl import ReplayBuffer

    buf = ReplayBuffer(100, seed=0)
    for start in range(0, 250, 50):
        buf.add_batch({
            "x": np.arange(start, start + 50, dtype=np.int64),
            "y": np.ones((50, 2), np.float32),
        })
    assert len(buf) == 100
    s = buf.sample(32)
    assert s["x"].shape == (32,) and s["y"].shape == (32, 2)
    # after wrapping, only the newest 100 values remain
    assert s["x"].min() >= 150


@pytest.mark.slow
def test_dqn_learns_cartpole(cluster):
    """DQN learning test (reference rllib learning-test pattern):
    double-Q + replay must clearly improve the mean return."""
    from ray_tpu.rl import DQNConfig

    algo = DQNConfig(
        num_env_runners=1,
        num_envs_per_runner=4,
        rollout_fragment_length=64,
        lr=1e-3,
        train_batch_size=64,
        updates_per_iteration=48,
        learning_starts=256,
        target_update_freq=100,
        epsilon_decay_steps=4000,
        seed=7,
    ).build()
    try:
        first = algo.train()["episode_return_mean"]
        last = first
        for _ in range(40):
            out = algo.train()
            last = out["episode_return_mean"]
            if last >= 60.0:
                break
        assert last >= 60.0 or last >= 2.5 * max(first, 15.0), (first, last)
    finally:
        algo.stop()


def test_dqn_state_roundtrip(cluster):
    from ray_tpu.rl import DQNConfig

    algo = DQNConfig(
        num_env_runners=1, num_envs_per_runner=2,
        rollout_fragment_length=16, learning_starts=16,
        updates_per_iteration=4, seed=9,
    ).build()
    try:
        algo.train()
        state = algo.get_state()
        obs = np.zeros(4, np.float32)
        before = algo.compute_single_action(obs)
        algo2 = DQNConfig(
            num_env_runners=1, num_envs_per_runner=2,
            rollout_fragment_length=16, seed=10,
        ).build()
        try:
            algo2.set_state(state)
            assert algo2.compute_single_action(obs) == before
            assert algo2.gradient_steps == algo.gradient_steps
        finally:
            algo2.stop()
    finally:
        algo.stop()


@pytest.mark.slow
def test_dqn_cnn_on_image_env(cluster):
    """The image-obs path end to end: CNN Q-network + custom image env
    resolved by module path on the runner workers (Atari stand-in)."""
    from ray_tpu.rl import DQNConfig

    algo = DQNConfig(
        env="ray_tpu.rl.test_envs:TinyImageEnv",
        model="cnn_q",
        num_env_runners=1,
        num_envs_per_runner=2,
        rollout_fragment_length=32,
        learning_starts=128,
        train_batch_size=32,
        updates_per_iteration=24,
        lr=2e-3,
        epsilon_decay_steps=1500,
        target_update_freq=50,
        seed=3,
    ).build()
    try:
        first = algo.train()["episode_return_mean"]
        last = first
        for _ in range(50):
            out = algo.train()
            last = out["episode_return_mean"]
            if last >= 12.5:  # optimal 16, random ~8
                break
        assert last >= 12.5, (first, last)
        obs = np.zeros((8, 8, 3), np.uint8)
        a = algo.compute_single_action(obs)
        assert a in (0, 1)
    finally:
        algo.stop()
