import asyncio

import pytest

from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.core.rpc import IoThread, RemoteError, RpcClient, RpcServer


@pytest.fixture
def io():
    t = IoThread("test-io")
    yield t
    t.stop()


def test_basic_call(io):
    async def setup():
        server = RpcServer()

        async def echo(payload, ctx):
            return ("echo", payload)

        server.register("echo", echo)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    client = RpcClient("127.0.0.1", port)
    assert io.run(client.call("echo", {"x": 1})) == ("echo", {"x": 1})
    io.run(client.close())
    io.run(server.stop())


def test_handler_error_propagates(io):
    async def setup():
        server = RpcServer()

        async def bad(payload, ctx):
            raise ValueError("server-side boom")

        server.register("bad", bad)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    client = RpcClient("127.0.0.1", port)
    with pytest.raises(ValueError, match="server-side boom"):
        io.run(client.call("bad"))
    io.run(client.close())
    io.run(server.stop())


def test_concurrent_calls(io):
    async def setup():
        server = RpcServer()

        async def slowecho(payload, ctx):
            await asyncio.sleep(0.01)
            return payload

        server.register("echo", slowecho)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    client = RpcClient("127.0.0.1", port)

    async def many():
        return await asyncio.gather(*[client.call("echo", i) for i in range(50)])

    assert io.run(many()) == list(range(50))
    io.run(client.close())
    io.run(server.stop())


def test_push_subscription(io):
    received = []

    async def setup():
        server = RpcServer()

        async def subscribe(payload, ctx):
            ctx.peer_tags["chan"] = payload
            asyncio.ensure_future(ctx.push(payload, {"msg": "hello"}))
            return "subscribed"

        server.register("subscribe", subscribe)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    client = RpcClient("127.0.0.1", port)
    client.subscribe_push(7, lambda m: received.append(m))
    assert io.run(client.call("subscribe", 7)) == "subscribed"
    import time

    for _ in range(100):
        if received:
            break
        time.sleep(0.01)
    assert received == [{"msg": "hello"}]
    io.run(client.close())
    io.run(server.stop())


def test_retry_reconnects(io):
    """Client retries when server comes up late / restarts."""

    async def setup():
        server = RpcServer()

        async def ping(payload, ctx):
            return "pong"

        server.register("ping", ping)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    client = RpcClient("127.0.0.1", port)
    assert io.run(client.call("ping")) == "pong"
    io.run(server.stop())
    GLOBAL_CONFIG.rpc_connect_timeout_s = 0.5
    try:
        with pytest.raises(Exception):
            io.run(client.call("ping", timeout=0.3))
    finally:
        GLOBAL_CONFIG.rpc_connect_timeout_s = 10.0

    async def restart():
        s2 = RpcServer(port=port)

        async def ping(payload, ctx):
            return "pong2"

        s2.register("ping", ping)
        await s2.start()
        return s2

    s2 = io.run(restart())
    assert io.run(client.call("ping", retries=5)) == "pong2"
    io.run(client.close())
    io.run(s2.stop())


def test_chaos_injection(io):
    async def setup():
        server = RpcServer()

        async def ping(payload, ctx):
            return "pong"

        server.register("ping", ping)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    client = RpcClient("127.0.0.1", port)
    GLOBAL_CONFIG.testing_rpc_failure = "ping:1.0"
    try:
        with pytest.raises(Exception, match="chaos"):
            io.run(client.call("ping"))
    finally:
        GLOBAL_CONFIG.testing_rpc_failure = ""
    assert io.run(client.call("ping")) == "pong"
    io.run(client.close())
    io.run(server.stop())
