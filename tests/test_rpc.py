import asyncio

import pytest

from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.core.rpc import IoThread, RemoteError, RpcClient, RpcServer


@pytest.fixture
def io():
    t = IoThread("test-io")
    yield t
    t.stop()


def test_basic_call(io):
    async def setup():
        server = RpcServer()

        async def echo(payload, ctx):
            return ("echo", payload)

        server.register("echo", echo)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    client = RpcClient("127.0.0.1", port)
    assert io.run(client.call("echo", {"x": 1})) == ("echo", {"x": 1})
    io.run(client.close())
    io.run(server.stop())


def test_handler_error_propagates(io):
    async def setup():
        server = RpcServer()

        async def bad(payload, ctx):
            raise ValueError("server-side boom")

        server.register("bad", bad)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    client = RpcClient("127.0.0.1", port)
    with pytest.raises(ValueError, match="server-side boom"):
        io.run(client.call("bad"))
    io.run(client.close())
    io.run(server.stop())


def test_concurrent_calls(io):
    async def setup():
        server = RpcServer()

        async def slowecho(payload, ctx):
            await asyncio.sleep(0.01)
            return payload

        server.register("echo", slowecho)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    client = RpcClient("127.0.0.1", port)

    async def many():
        return await asyncio.gather(*[client.call("echo", i) for i in range(50)])

    assert io.run(many()) == list(range(50))
    io.run(client.close())
    io.run(server.stop())


def test_push_subscription(io):
    received = []

    async def setup():
        server = RpcServer()

        async def subscribe(payload, ctx):
            ctx.peer_tags["chan"] = payload
            asyncio.ensure_future(ctx.push(payload, {"msg": "hello"}))
            return "subscribed"

        server.register("subscribe", subscribe)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    client = RpcClient("127.0.0.1", port)
    client.subscribe_push(7, lambda m: received.append(m))
    assert io.run(client.call("subscribe", 7)) == "subscribed"
    import time

    for _ in range(100):
        if received:
            break
        time.sleep(0.01)
    assert received == [{"msg": "hello"}]
    io.run(client.close())
    io.run(server.stop())


def test_retry_reconnects(io):
    """Client retries when server comes up late / restarts."""

    async def setup():
        server = RpcServer()

        async def ping(payload, ctx):
            return "pong"

        server.register("ping", ping)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    client = RpcClient("127.0.0.1", port)
    assert io.run(client.call("ping")) == "pong"
    io.run(server.stop())
    GLOBAL_CONFIG.rpc_connect_timeout_s = 0.5
    try:
        with pytest.raises(Exception):
            io.run(client.call("ping", timeout=0.3))
    finally:
        GLOBAL_CONFIG.rpc_connect_timeout_s = 10.0

    async def restart():
        s2 = RpcServer(port=port)

        async def ping(payload, ctx):
            return "pong2"

        s2.register("ping", ping)
        await s2.start()
        return s2

    s2 = io.run(restart())
    assert io.run(client.call("ping", retries=5)) == "pong2"
    io.run(client.close())
    io.run(s2.stop())


def _parse_wire(wire):
    """Parse raw wire bytes into (n_frames, messages-in-order)."""
    import msgpack

    from ray_tpu.core import rpc

    frames = []
    off = 0
    while off < len(wire):
        (ln,) = rpc._LEN.unpack_from(wire, off)
        off += rpc._LEN.size
        frames.append(msgpack.unpackb(wire[off : off + ln], raw=True, use_list=True))
        off += ln
    msgs = [m for f in frames for m in rpc._iter_messages(f)]
    return frames, msgs


def test_batch_wire_coalesces_and_preserves_fifo():
    """Micro-batching wire form: one flush of N frames becomes one BATCH
    frame; expansion yields the messages in exactly the queued order."""
    from ray_tpu.core import rpc

    bodies = [
        rpc._encode_body(rpc.REQUEST, i, b"m", b"p%d" % i) for i in range(10)
    ]
    frames, msgs = _parse_wire(rpc._wire_from_bodies(bodies))
    assert len(frames) == 1
    assert frames[0][0] == rpc.BATCH
    assert [m[1] for m in msgs] == list(range(10))
    assert [bytes(m[3]) for m in msgs] == [b"p%d" % i for i in range(10)]
    # a single queued frame travels plain (no batch wrapper)
    frames1, msgs1 = _parse_wire(rpc._wire_from_bodies(bodies[:1]))
    assert len(frames1) == 1 and frames1[0][0] == rpc.REQUEST


def test_batch_wire_respects_caps():
    """rpc_batch_max_frames / rpc_batch_max_bytes split a flush into
    several batch frames, still in FIFO order; singleton groups travel
    as plain frames."""
    from ray_tpu.core import rpc

    bodies = [
        rpc._encode_body(rpc.REQUEST, i, b"m", b"x" * 10) for i in range(10)
    ]
    old_frames = GLOBAL_CONFIG.rpc_batch_max_frames
    old_bytes = GLOBAL_CONFIG.rpc_batch_max_bytes
    try:
        GLOBAL_CONFIG.rpc_batch_max_frames = 4
        frames, msgs = _parse_wire(rpc._wire_from_bodies(bodies))
        assert [f[0] for f in frames] == [rpc.BATCH, rpc.BATCH, rpc.BATCH]
        assert [len(list(rpc._iter_messages(f))) for f in frames] == [4, 4, 2]
        assert [m[1] for m in msgs] == list(range(10))
        # byte cap of 1: every body overflows the group → all plain frames
        GLOBAL_CONFIG.rpc_batch_max_frames = 64
        GLOBAL_CONFIG.rpc_batch_max_bytes = 1
        frames, msgs = _parse_wire(rpc._wire_from_bodies(bodies))
        assert [f[0] for f in frames] == [rpc.REQUEST] * 10
        assert [m[1] for m in msgs] == list(range(10))
    finally:
        GLOBAL_CONFIG.rpc_batch_max_frames = old_frames
        GLOBAL_CONFIG.rpc_batch_max_bytes = old_bytes


def test_batched_dispatch_order_end_to_end(io):
    """Requests issued in one loop pass coalesce into batch frames; the
    server must enter their handlers in submission (FIFO) order."""
    order = []

    async def setup():
        server = RpcServer()

        async def note(payload, ctx):
            order.append(payload)  # appended before any await → dispatch order
            return payload

        server.register("note", note)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    client = RpcClient("127.0.0.1", port)

    async def many():
        return await asyncio.gather(*[client.call("note", i) for i in range(100)])

    assert io.run(many()) == list(range(100))
    assert order == list(range(100))
    io.run(client.close())
    io.run(server.stop())


def test_batch_chaos_retries_without_duplicate_side_effects(io):
    """Injected failures fire BEFORE the handler runs (rpc_chaos
    contract), so a batch frame that dies mid-flight retries without
    duplicating side effects — every op lands exactly once."""
    counts = {}

    async def setup():
        server = RpcServer()

        async def incr(payload, ctx):
            counts[payload] = counts.get(payload, 0) + 1
            return payload

        server.register("incr", incr)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    client = RpcClient("127.0.0.1", port)
    GLOBAL_CONFIG.testing_rpc_failure = "incr:0.3"
    try:

        async def many():
            return await asyncio.gather(
                *[client.call("incr", i, retries=100) for i in range(40)]
            )

        assert sorted(io.run(many())) == list(range(40))
    finally:
        GLOBAL_CONFIG.testing_rpc_failure = ""
    assert {k: v for k, v in counts.items() if v != 1} == {}
    io.run(client.close())
    io.run(server.stop())


def test_chaos_injection(io):
    async def setup():
        server = RpcServer()

        async def ping(payload, ctx):
            return "pong"

        server.register("ping", ping)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    client = RpcClient("127.0.0.1", port)
    GLOBAL_CONFIG.testing_rpc_failure = "ping:1.0"
    try:
        with pytest.raises(Exception, match="chaos"):
            io.run(client.call("ping"))
    finally:
        GLOBAL_CONFIG.testing_rpc_failure = ""
    assert io.run(client.call("ping")) == "pong"
    io.run(client.close())
    io.run(server.stop())
