import asyncio
import contextlib
import time

import pytest

from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.core.rpc import (
    ChaosInjectedError,
    ConnectionLost,
    IoThread,
    RemoteError,
    RpcClient,
    RpcServer,
)


@pytest.fixture
def io():
    t = IoThread("test-io")
    yield t
    t.stop()


@contextlib.contextmanager
def chaos_plan(spec: str, seed: int = 1234):
    """Activate a seeded fault plan for the duration of a test."""
    old_spec = GLOBAL_CONFIG.testing_rpc_chaos
    old_seed = GLOBAL_CONFIG.testing_rpc_chaos_seed
    GLOBAL_CONFIG.testing_rpc_chaos = spec
    GLOBAL_CONFIG.testing_rpc_chaos_seed = seed
    try:
        yield
    finally:
        GLOBAL_CONFIG.testing_rpc_chaos = old_spec
        GLOBAL_CONFIG.testing_rpc_chaos_seed = old_seed


def _counting_server(io, method="incr"):
    """Server whose handler counts executions per key (the side-effect
    detector every dedup test asserts against)."""
    counts = {}

    async def setup():
        server = RpcServer()

        async def incr(payload, ctx):
            counts[payload] = counts.get(payload, 0) + 1
            return ("ok", payload)

        server.register(method, incr)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    return server, port, counts


def test_basic_call(io):
    async def setup():
        server = RpcServer()

        async def echo(payload, ctx):
            return ("echo", payload)

        server.register("echo", echo)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    client = RpcClient("127.0.0.1", port)
    assert io.run(client.call("echo", {"x": 1})) == ("echo", {"x": 1})
    io.run(client.close())
    io.run(server.stop())


def test_handler_error_propagates(io):
    async def setup():
        server = RpcServer()

        async def bad(payload, ctx):
            raise ValueError("server-side boom")

        server.register("bad", bad)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    client = RpcClient("127.0.0.1", port)
    with pytest.raises(ValueError, match="server-side boom"):
        io.run(client.call("bad"))
    io.run(client.close())
    io.run(server.stop())


def test_concurrent_calls(io):
    async def setup():
        server = RpcServer()

        async def slowecho(payload, ctx):
            await asyncio.sleep(0.01)
            return payload

        server.register("echo", slowecho)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    client = RpcClient("127.0.0.1", port)

    async def many():
        return await asyncio.gather(*[client.call("echo", i) for i in range(50)])

    assert io.run(many()) == list(range(50))
    io.run(client.close())
    io.run(server.stop())


def test_push_subscription(io):
    received = []

    async def setup():
        server = RpcServer()

        async def subscribe(payload, ctx):
            ctx.peer_tags["chan"] = payload
            asyncio.ensure_future(ctx.push(payload, {"msg": "hello"}))
            return "subscribed"

        server.register("subscribe", subscribe)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    client = RpcClient("127.0.0.1", port)
    client.subscribe_push(7, lambda m: received.append(m))
    assert io.run(client.call("subscribe", 7)) == "subscribed"
    import time

    for _ in range(100):
        if received:
            break
        time.sleep(0.01)
    assert received == [{"msg": "hello"}]
    io.run(client.close())
    io.run(server.stop())


def test_retry_reconnects(io):
    """Client retries when server comes up late / restarts."""

    async def setup():
        server = RpcServer()

        async def ping(payload, ctx):
            return "pong"

        server.register("ping", ping)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    client = RpcClient("127.0.0.1", port)
    assert io.run(client.call("ping")) == "pong"
    io.run(server.stop())
    GLOBAL_CONFIG.rpc_connect_timeout_s = 0.5
    try:
        with pytest.raises(Exception):
            io.run(client.call("ping", timeout=0.3))
    finally:
        GLOBAL_CONFIG.rpc_connect_timeout_s = 10.0

    async def restart():
        s2 = RpcServer(port=port)

        async def ping(payload, ctx):
            return "pong2"

        s2.register("ping", ping)
        await s2.start()
        return s2

    s2 = io.run(restart())
    assert io.run(client.call("ping", retries=5)) == "pong2"
    io.run(client.close())
    io.run(s2.stop())


def _parse_wire(wire):
    """Parse raw wire bytes into (n_frames, messages-in-order)."""
    import msgpack

    from ray_tpu.core import rpc

    frames = []
    off = 0
    while off < len(wire):
        (ln,) = rpc._LEN.unpack_from(wire, off)
        off += rpc._LEN.size
        frames.append(msgpack.unpackb(wire[off : off + ln], raw=True, use_list=True))
        off += ln
    msgs = [m for f in frames for m in rpc._iter_messages(f)]
    return frames, msgs


def test_batch_wire_coalesces_and_preserves_fifo():
    """Micro-batching wire form: one flush of N frames becomes one BATCH
    frame; expansion yields the messages in exactly the queued order."""
    from ray_tpu.core import rpc

    bodies = [
        rpc._encode_body(rpc.REQUEST, i, b"m", b"p%d" % i) for i in range(10)
    ]
    frames, msgs = _parse_wire(rpc._wire_from_bodies(bodies))
    assert len(frames) == 1
    assert frames[0][0] == rpc.BATCH
    assert [m[1] for m in msgs] == list(range(10))
    assert [bytes(m[3]) for m in msgs] == [b"p%d" % i for i in range(10)]
    # a single queued frame travels plain (no batch wrapper)
    frames1, msgs1 = _parse_wire(rpc._wire_from_bodies(bodies[:1]))
    assert len(frames1) == 1 and frames1[0][0] == rpc.REQUEST


def test_batch_wire_respects_caps():
    """rpc_batch_max_frames / rpc_batch_max_bytes split a flush into
    several batch frames, still in FIFO order; singleton groups travel
    as plain frames."""
    from ray_tpu.core import rpc

    bodies = [
        rpc._encode_body(rpc.REQUEST, i, b"m", b"x" * 10) for i in range(10)
    ]
    old_frames = GLOBAL_CONFIG.rpc_batch_max_frames
    old_bytes = GLOBAL_CONFIG.rpc_batch_max_bytes
    try:
        GLOBAL_CONFIG.rpc_batch_max_frames = 4
        frames, msgs = _parse_wire(rpc._wire_from_bodies(bodies))
        assert [f[0] for f in frames] == [rpc.BATCH, rpc.BATCH, rpc.BATCH]
        assert [len(list(rpc._iter_messages(f))) for f in frames] == [4, 4, 2]
        assert [m[1] for m in msgs] == list(range(10))
        # byte cap of 1: every body overflows the group → all plain frames
        GLOBAL_CONFIG.rpc_batch_max_frames = 64
        GLOBAL_CONFIG.rpc_batch_max_bytes = 1
        frames, msgs = _parse_wire(rpc._wire_from_bodies(bodies))
        assert [f[0] for f in frames] == [rpc.REQUEST] * 10
        assert [m[1] for m in msgs] == list(range(10))
    finally:
        GLOBAL_CONFIG.rpc_batch_max_frames = old_frames
        GLOBAL_CONFIG.rpc_batch_max_bytes = old_bytes


def test_batched_dispatch_order_end_to_end(io):
    """Requests issued in one loop pass coalesce into batch frames; the
    server must enter their handlers in submission (FIFO) order."""
    order = []

    async def setup():
        server = RpcServer()

        async def note(payload, ctx):
            order.append(payload)  # appended before any await → dispatch order
            return payload

        server.register("note", note)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    client = RpcClient("127.0.0.1", port)

    async def many():
        return await asyncio.gather(*[client.call("note", i) for i in range(100)])

    assert io.run(many()) == list(range(100))
    assert order == list(range(100))
    io.run(client.close())
    io.run(server.stop())


def test_batch_chaos_retries_without_duplicate_side_effects(io):
    """Injected failures fire BEFORE the handler runs (rpc_chaos
    contract), so a batch frame that dies mid-flight retries without
    duplicating side effects — every op lands exactly once."""
    counts = {}

    async def setup():
        server = RpcServer()

        async def incr(payload, ctx):
            counts[payload] = counts.get(payload, 0) + 1
            return payload

        server.register("incr", incr)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    client = RpcClient("127.0.0.1", port)
    GLOBAL_CONFIG.testing_rpc_failure = "incr:0.3"
    try:

        async def many():
            return await asyncio.gather(
                *[client.call("incr", i, retries=100) for i in range(40)]
            )

        assert sorted(io.run(many())) == list(range(40))
    finally:
        GLOBAL_CONFIG.testing_rpc_failure = ""
    assert {k: v for k, v in counts.items() if v != 1} == {}
    io.run(client.close())
    io.run(server.stop())


def test_chaos_injection(io):
    async def setup():
        server = RpcServer()

        async def ping(payload, ctx):
            return "pong"

        server.register("ping", ping)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    client = RpcClient("127.0.0.1", port)
    GLOBAL_CONFIG.testing_rpc_failure = "ping:1.0"
    try:
        with pytest.raises(Exception, match="chaos"):
            io.run(client.call("ping"))
    finally:
        GLOBAL_CONFIG.testing_rpc_failure = ""
    assert io.run(client.call("ping")) == "pong"
    io.run(client.close())
    io.run(server.stop())


# ---------------------------------------------------------------------------
# seeded fault plan: four chaos modes + determinism


def test_fault_plan_determinism():
    """Same seed + same consult sequence ⇒ identical injection sequence
    (the reproduce-from-the-log contract); a different seed diverges."""
    from ray_tpu.util.chaos import RpcFaultPlan

    spec = "kv_put:reply_drop:0.5,*:delay:0.2:0.01"
    methods = ["kv_put", "ping", "kv_put", "submit", "kv_put", "ping"] * 50
    a = RpcFaultPlan(spec, seed=7)
    b = RpcFaultPlan(spec, seed=7)
    seq_a = [a.next_fault(m) for m in methods]
    seq_b = [b.next_fault(m) for m in methods]
    assert seq_a == seq_b
    assert a.consults == len(methods)
    assert any(f is not None for f in seq_a)  # the plan actually fires
    c = RpcFaultPlan(spec, seed=8)
    assert [c.next_fault(m) for m in methods] != seq_a


def test_fault_plan_rejects_bad_spec():
    from ray_tpu.util.chaos import RpcFaultPlan

    with pytest.raises(ValueError, match="unknown rpc chaos mode"):
        RpcFaultPlan("kv_put:explode:0.5", seed=1)
    with pytest.raises(ValueError, match="need method:mode:prob"):
        RpcFaultPlan("kv_put", seed=1)


def test_chaos_request_drop_mode(io):
    """request_drop fires BEFORE the handler: at prob 1.0 the call fails
    (after the internal chaos-retry budget) and the handler NEVER ran."""
    server, port, counts = _counting_server(io)
    client = RpcClient("127.0.0.1", port)
    with chaos_plan("incr:request_drop:1.0"):
        with pytest.raises(ChaosInjectedError):
            io.run(client.call("incr", "a"))
    assert counts == {}
    assert io.run(client.call("incr", "a")) == ("ok", "a")
    assert counts == {"a": 1}
    io.run(client.close())
    io.run(server.stop())


def test_chaos_delay_mode(io):
    """delay injects latency before the handler and otherwise leaves the
    call intact."""
    server, port, counts = _counting_server(io)
    client = RpcClient("127.0.0.1", port)
    with chaos_plan("incr:delay:1.0:0.2"):
        t0 = time.monotonic()
        assert io.run(client.call("incr", "a")) == ("ok", "a")
        assert time.monotonic() - t0 >= 0.2
    assert counts == {"a": 1}
    io.run(client.close())
    io.run(server.stop())


def test_chaos_disconnect_mode(io):
    """disconnect hard-resets the connection mid-call: the client sees
    ConnectionLost (NOT a chaos reply), reconnects, and a later call
    succeeds once injection stops."""
    server, port, counts = _counting_server(io)
    client = RpcClient("127.0.0.1", port)
    with chaos_plan("incr:disconnect:1.0"):
        with pytest.raises(ConnectionLost):
            io.run(client.call("incr", "a", retries=2, connect_timeout=2.0))
    assert counts == {}  # reset fired before the handler
    assert io.run(client.call("incr", "a")) == ("ok", "a")
    assert counts == {"a": 1}
    io.run(client.close())
    io.run(server.stop())


def test_reply_drop_dedup_executes_exactly_once(io):
    """THE duplicate-execution trap: reply_drop runs the handler then
    loses the reply. With request-id dedup the retries are answered from
    the reply cache — every mutating op lands exactly once across N
    retries, and the dedup-hit counter proves the cache did the work."""
    from ray_tpu.observability import metrics as m
    from ray_tpu.observability.rpc_metrics import RPC_DEDUP_HITS

    server, port, counts = _counting_server(io)
    client = RpcClient("127.0.0.1", port)
    before = RPC_DEDUP_HITS._values.get(("incr",), 0.0)
    with chaos_plan("incr:reply_drop:0.5", seed=42):

        async def many():
            return await asyncio.gather(
                *[client.call("incr", i, retries=50) for i in range(40)]
            )

        out = io.run(many())
    assert sorted(p for _ok, p in out) == list(range(40))
    assert {k: v for k, v in counts.items() if v != 1} == {}
    assert RPC_DEDUP_HITS._values.get(("incr",), 0.0) > before
    # counters reach the Prometheus exposition too
    assert "raytpu_rpc_dedup_hits_total" in m.render()
    assert 'raytpu_rpc_chaos_injections_total{mode="reply_drop"}' in m.render()
    io.run(client.close())
    io.run(server.stop())


def test_reply_drop_without_dedup_duplicates(io):
    """Negative control: with dedup opted out, a reply_drop retry
    re-executes the handler — the duplicate the cache exists to stop."""
    server, port, counts = _counting_server(io)
    client = RpcClient("127.0.0.1", port)
    with chaos_plan("incr:reply_drop:0.5", seed=42):

        async def many():
            return await asyncio.gather(
                *[
                    client.call("incr", i, retries=50, dedup=False)
                    for i in range(20)
                ]
            )

        io.run(many())
    assert any(v > 1 for v in counts.values()), counts
    io.run(client.close())
    io.run(server.stop())


def test_duplicate_in_flight_request_executes_once(io):
    """A duplicate arriving while the ORIGINAL execution is still running
    awaits its in-flight future instead of executing again."""
    calls = {"n": 0}

    async def setup():
        server = RpcServer()

        async def slow_incr(payload, ctx):
            calls["n"] += 1
            await asyncio.sleep(0.3)
            return calls["n"]

        server.register("slow_incr", slow_incr)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    client = RpcClient("127.0.0.1", port)

    async def dup():
        rid = client.next_request_id()
        return await asyncio.gather(
            client.call("slow_incr", None, request_id=rid),
            client.call("slow_incr", None, request_id=rid),
        )

    assert io.run(dup()) == [1, 1]
    assert calls["n"] == 1
    io.run(client.close())
    io.run(server.stop())


def test_dedup_cache_eviction_bounded_oldest_first(io):
    """The reply cache is bounded: over the entry cap the OLDEST entries
    evict first; a byte cap alone also bounds it."""
    server, port, counts = _counting_server(io)
    client = RpcClient("127.0.0.1", port)
    old_entries = GLOBAL_CONFIG.rpc_dedup_cache_entries
    old_bytes = GLOBAL_CONFIG.rpc_dedup_cache_max_bytes
    try:
        GLOBAL_CONFIG.rpc_dedup_cache_entries = 4
        for i in range(6):
            io.run(client.call("incr", i))
        assert len(server._dedup_done) == 4
        kept_rids = sorted(k[1] for k in server._dedup_done)
        assert kept_rids == kept_rids[:1] + list(
            range(kept_rids[0] + 1, kept_rids[0] + 4)
        )  # contiguous newest window
        all_rids_seen = 6
        assert min(kept_rids) > all_rids_seen - 4  # oldest two are gone
        # byte cap: small enough that every insert immediately evicts
        GLOBAL_CONFIG.rpc_dedup_cache_entries = old_entries
        GLOBAL_CONFIG.rpc_dedup_cache_max_bytes = 1
        io.run(client.call("incr", 99))
        assert len(server._dedup_done) == 0
        assert server._dedup_bytes == 0
    finally:
        GLOBAL_CONFIG.rpc_dedup_cache_entries = old_entries
        GLOBAL_CONFIG.rpc_dedup_cache_max_bytes = old_bytes
    io.run(client.close())
    io.run(server.stop())


def test_retry_backoff_capped_by_ambient_deadline(io):
    """The retry loop's backoff (and stop condition) honors the ambient
    core/deadline budget: with the server gone, a generous retry budget
    still fails within the deadline instead of sleeping through it."""
    from ray_tpu.core.deadline import deadline_scope

    server, port, _counts = _counting_server(io)
    io.run(server.stop())

    client = RpcClient("127.0.0.1", port)

    async def run():
        with deadline_scope(0.5):
            await client.call("incr", 1, retries=50, connect_timeout=0.1)

    t0 = time.monotonic()
    with pytest.raises((ConnectionLost, asyncio.TimeoutError)):
        io.run(run())
    assert time.monotonic() - t0 < 3.0
    io.run(client.close())


def test_rpc_retry_counter_increments(io):
    from ray_tpu.observability.rpc_metrics import RPC_RETRIES

    server, port, counts = _counting_server(io)
    client = RpcClient("127.0.0.1", port)
    before = RPC_RETRIES._values.get(("incr",), 0.0)
    with chaos_plan("incr:reply_drop:0.5", seed=43):
        io.run(client.call("incr", "x", retries=50))
    assert RPC_RETRIES._values.get(("incr",), 0.0) > before
    assert counts == {"x": 1}
    io.run(client.close())
    io.run(server.stop())


def test_idempotent_methods_namespaced_per_role(io):
    """The idempotent classification is per SERVER ROLE: "stats" is a
    pure read on node daemons, but a same-named MUTATING handler on a
    different service must still ride the dedup cache — a process-global
    set would silently skip stamping for it (the PR 5 deferred finding).
    An untagged client keeps the legacy union behavior."""
    from ray_tpu.core.rpc import idempotent_methods

    # the classification itself
    assert "stats" in idempotent_methods("noded")
    assert "stats" not in idempotent_methods("controller")
    assert "stats" in idempotent_methods(None)  # legacy union
    assert "kv_get" in idempotent_methods("controller")
    assert "kv_get" not in idempotent_methods("worker")

    # wire behavior: a mutating "stats" on a non-noded role dedups its
    # retries; the same calls from a noded-tagged client re-execute
    server, port, counts = _counting_server(io, method="stats")
    with chaos_plan("stats:reply_drop:0.6", seed=77):
        tagged = RpcClient("127.0.0.1", port, role="controller")
        for i in range(8):
            io.run(tagged.call("stats", ("c", i), retries=50))
        io.run(tagged.close())
    # every logical call executed exactly once despite dropped replies
    assert counts == {("c", i): 1 for i in range(8)}, counts

    # negative control: the noded classification treats "stats" as a
    # pure read -> no request-id meta -> a retried reply_drop re-executes
    counts.clear()
    with chaos_plan("stats:reply_drop:0.6", seed=78):
        noded = RpcClient("127.0.0.1", port, role="noded")
        for i in range(8):
            io.run(noded.call("stats", ("n", i), retries=50))
        io.run(noded.close())
    assert sum(counts.values()) > 8, counts  # at least one re-execution
    io.run(server.stop())


# ---------------------------------------------------------------------------
# RAW frames (kind 5): zero-copy out-of-band payload framing


def _raw_server(io):
    """Server whose ``blob`` handler answers with a RAW frame sliced out
    of a source buffer (a stand-in for a shm segment window); ``crc``
    rides the frame header. ``closes`` counts release-hook invocations."""
    import zlib

    from ray_tpu.core.rpc import RawPayload, RpcServer

    src = bytes(range(256)) * 4096  # 1 MiB, patterned
    closes = []

    async def setup():
        server = RpcServer()

        async def blob(payload, ctx):
            off, ln = payload["offset"], payload["length"]
            view = memoryview(src)[off : off + ln]
            return RawPayload(
                view, meta=zlib.crc32(view), close=lambda: closes.append(1)
            )

        server.register("blob", blob)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    return server, port, src, closes


def test_raw_reply_into_caller_buffer(io):
    """A RAW reply lands DIRECTLY in the caller-provided buffer; the
    header meta (crc) rides along; the sender's close hook runs."""
    import zlib

    from ray_tpu.core.rpc import RawReply, RpcClient

    server, port, src, closes = _raw_server(io)
    client = RpcClient("127.0.0.1", port)
    sink = bytearray(64 * 1024)
    reply = io.run(
        client.call(
            "blob", {"offset": 512, "length": 64 * 1024},
            raw_into=memoryview(sink),
        )
    )
    assert isinstance(reply, RawReply)
    assert reply.nbytes == 64 * 1024 and reply.data is None
    assert bytes(sink) == src[512 : 512 + 64 * 1024]
    assert reply.meta == zlib.crc32(sink)
    assert closes, "sender close hook never ran"
    io.run(client.close())
    io.run(server.stop())


def test_raw_reply_zero_length_and_oversized(io):
    """Edge cases: a zero-length RAW payload resolves cleanly; a payload
    larger than the sink falls back to materialized data (the stream
    stays in sync either way — a following call still works)."""
    from ray_tpu.core.rpc import RawReply, RpcClient

    server, port, src, _closes = _raw_server(io)
    client = RpcClient("127.0.0.1", port)
    # zero-length
    sink = bytearray(16)
    reply = io.run(
        client.call("blob", {"offset": 0, "length": 0}, raw_into=memoryview(sink))
    )
    assert isinstance(reply, RawReply) and reply.nbytes == 0 and reply.data is None
    # oversized for the sink: materialized fallback, bytes still exact
    small = bytearray(1024)
    reply = io.run(
        client.call(
            "blob", {"offset": 0, "length": 8 * 1024},
            raw_into=memoryview(small),
        )
    )
    assert isinstance(reply, RawReply) and reply.nbytes == 8 * 1024
    assert bytes(reply.data) == src[: 8 * 1024]
    # stream still framed correctly afterwards
    sink2 = bytearray(4096)
    reply = io.run(
        client.call("blob", {"offset": 4096, "length": 4096}, raw_into=memoryview(sink2))
    )
    assert bytes(sink2) == src[4096:8192]
    io.run(client.close())
    io.run(server.stop())


def test_raw_reply_without_sink_materializes(io):
    """A plain call answered with a RAW frame still gets the payload —
    as RawReply.data (the no-sink fallback), byte-exact."""
    from ray_tpu.core.rpc import RawReply, RpcClient

    server, port, src, _closes = _raw_server(io)
    client = RpcClient("127.0.0.1", port)
    reply = io.run(client.call("blob", {"offset": 100, "length": 3000}))
    assert isinstance(reply, RawReply)
    assert bytes(reply.data) == src[100:3100]
    io.run(client.close())
    io.run(server.stop())


def test_raw_replies_never_enter_dedup_cache(io):
    """THE cache-churn guard: a dedup-stamped request answered RAW must
    not put megabytes into the bounded reply cache — the cache stays
    empty and duplicate retries re-execute (the raw methods are
    idempotent reads by classification)."""
    from ray_tpu.core.rpc import RpcClient

    server, port, _src, _closes = _raw_server(io)
    client = RpcClient("127.0.0.1", port)
    sink = bytearray(4096)
    # force a dedup stamp onto the raw call (real raw methods are
    # classified idempotent and never stamp; this is the worst case)
    rid = client.next_request_id()
    reply = io.run(
        client.call(
            "blob", {"offset": 0, "length": 4096},
            raw_into=memoryview(sink), request_id=rid, dedup=True,
        )
    )
    assert reply.nbytes == 4096
    assert len(server._dedup_done) == 0  # noqa: SLF001 — the assertion
    assert server._dedup_bytes == 0  # noqa: SLF001
    io.run(client.close())
    io.run(server.stop())


def test_raw_push_reassembles_envelope(io):
    """RAW pushes (streaming-item transport): the pickled envelope rides
    the frame header, the bulk payload out-of-band, and the subscriber's
    handler receives the reassembled dict — same contract as push()."""
    import threading

    from ray_tpu.core.rpc import RpcClient, RpcServer

    got = []
    ev = threading.Event()
    payload_bytes = bytes(range(256)) * 200  # 50 KiB

    async def setup():
        server = RpcServer()

        async def kick(payload, ctx):
            await ctx.push_raw(
                9, {"task_id": b"t1", "index": 3, "kind": "inline"},
                payload_bytes,
            )
            return "ok"

        server.register("kick", kick)
        port = await server.start()
        return server, port

    server, port = io.run(setup())
    client = RpcClient("127.0.0.1", port)

    def on_push(msg):
        got.append(msg)
        ev.set()

    client.subscribe_push(9, on_push)
    assert io.run(client.call("kick")) == "ok"
    assert ev.wait(10)
    (msg,) = got
    assert msg["task_id"] == b"t1" and msg["index"] == 3
    assert msg["data"] == payload_bytes
    io.run(client.close())
    io.run(server.stop())
