"""Runtime-env code shipping: working_dir / py_modules zip -> controller
KV -> worker-side per-hash extract + sys.path (reference
``_private/runtime_env/packaging.py`` behind the ``plugin.py:24`` ABC)."""

import os
import sys
import textwrap
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture()
def project(tmp_path):
    """A driver-only 'project': a module + a package that exist nowhere
    on the workers' import path."""
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "driver_only_mod.py").write_text(
        "SECRET = 'from-working-dir'\n"
        "def shout():\n"
        "    return SECRET.upper()\n"
    )
    (proj / "datafile.txt").write_text("payload-bytes")
    lib = tmp_path / "libs" / "driver_only_pkg"
    lib.mkdir(parents=True)
    (lib / "__init__.py").write_text("NAME = 'driver-only-pkg'\n")
    (lib / "inner.py").write_text("def nine():\n    return 9\n")
    return proj, lib


def test_working_dir_ships_to_second_node(project):
    """The VERDICT done-criterion: a task scheduled on a SECOND node
    imports a module that exists only in the driver's working_dir."""
    proj, _lib = project
    cluster = Cluster(num_cpus=1)
    cluster.add_node(num_cpus=2, resources={"other": 2})
    time.sleep(1.0)
    ray_tpu.init(address=cluster.address)
    try:

        @ray_tpu.remote(
            num_cpus=1,
            resources={"other": 1},  # forces the second node
            runtime_env={"working_dir": str(proj)},
        )
        def use_module():
            import driver_only_mod

            # working_dir contents are also present as files for
            # dedicated workers; pooled task workers get sys.path
            return driver_only_mod.shout()

        assert ray_tpu.get(use_module.remote(), timeout=120) == "FROM-WORKING-DIR"
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


@pytest.fixture(scope="module")
def local_cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_py_modules_package_and_file(local_cluster, tmp_path):
    lib = tmp_path / "libs" / "only_pkg"
    lib.mkdir(parents=True)
    (lib / "__init__.py").write_text("VALUE = 31\n")
    single = tmp_path / "only_file.py"
    single.write_text("def f():\n    return 'single-file'\n")

    @ray_tpu.remote(
        runtime_env={"py_modules": [str(lib), str(single)]}
    )
    def use_both():
        import only_pkg
        import only_file

        return only_pkg.VALUE, only_file.f()

    assert ray_tpu.get(use_both.remote(), timeout=120) == (31, "single-file")


def test_working_dir_actor_chdir(local_cluster, tmp_path):
    """Dedicated actor workers chdir into the extracted working_dir —
    relative file access works (reference working_dir semantics)."""
    proj = tmp_path / "actorproj"
    proj.mkdir()
    (proj / "config.txt").write_text("chdir-proof")

    @ray_tpu.remote(runtime_env={"working_dir": str(proj)})
    class Reader:
        def read(self):
            with open("config.txt") as f:
                return f.read()

    r = Reader.remote()
    assert ray_tpu.get(r.read.remote(), timeout=120) == "chdir-proof"
    ray_tpu.kill(r)


def test_runtime_env_validation_errors(local_cluster, tmp_path):
    with pytest.raises(ValueError, match="not a directory"):
        @ray_tpu.remote(runtime_env={"working_dir": str(tmp_path / "nope")})
        def f():
            return 1

        f.remote()
    with pytest.raises(ValueError, match="unknown runtime_env key"):
        @ray_tpu.remote(runtime_env={"pip": ["requests"]})
        def g():
            return 1

        g.remote()


def test_package_cache_single_upload(local_cluster, tmp_path):
    """Same working_dir twice → one KV package (content-addressed)."""
    proj = tmp_path / "cachedproj"
    proj.mkdir()
    (proj / "m.py").write_text("X = 1\n")

    from ray_tpu.core.api import _global_worker

    before = len(_global_worker().backend.kv_keys(b"runtime_env_pkg:"))

    @ray_tpu.remote(runtime_env={"working_dir": str(proj)})
    def one():
        import m

        return m.X

    assert ray_tpu.get(one.remote(), timeout=120) == 1
    assert ray_tpu.get(one.remote(), timeout=120) == 1
    after = len(_global_worker().backend.kv_keys(b"runtime_env_pkg:"))
    assert after - before == 1  # two submissions, one content-addressed pkg
