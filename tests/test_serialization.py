import numpy as np
import pytest

from ray_tpu.core import serialization


def roundtrip(value):
    ser = serialization.serialize(value)
    return serialization.deserialize_bytes(ser.to_bytes())


def test_basic_types():
    for v in [1, "x", None, True, [1, 2, {"a": (3, 4)}], {"k": b"bytes"}]:
        assert roundtrip(v) == v


def test_numpy_zero_copy_out_of_band():
    arr = np.arange(1000, dtype=np.float32)
    ser = serialization.serialize(arr)
    # array data must travel out-of-band, not inside the pickle meta
    assert len(ser.buffers) >= 1
    assert len(ser.meta) < 500
    out = roundtrip(arr)
    np.testing.assert_array_equal(out, arr)


def test_closure():
    x = 41

    def f(y):
        return x + y

    assert roundtrip(f)(1) == 42


def test_custom_serializer():
    class Weird:
        def __init__(self, v):
            self.v = v

        def __reduce__(self):
            raise RuntimeError("not picklable")

    serialization.register_serializer(
        Weird, serializer=lambda w: w.v, deserializer=lambda v: Weird(v)
    )
    try:
        assert roundtrip(Weird(5)).v == 5
    finally:
        serialization.deregister_serializer(Weird)
    with pytest.raises(Exception):
        roundtrip(Weird(5))
