"""ray_tpu.serve tests: deploy/route/scale/HTTP (reference test model:
``serve/tests/`` + ``_private/local_testing_mode.py``)."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_deploy_and_call(cluster):
    @serve.deployment(num_replicas=2, ray_actor_options={"num_cpus": 0.25})
    class Adder:
        def __init__(self, bias):
            self.bias = bias

        def __call__(self, x):
            return x + self.bias

        def bias_value(self):
            return self.bias

    handle = serve.run(Adder.bind(10))
    assert ray_tpu.get(handle.remote(5), timeout=60) == 15
    assert ray_tpu.get(handle.method("bias_value")(), timeout=30) == 10
    assert serve.status()["Adder"]["replicas"] == 2
    serve.delete("Adder")


def test_function_deployment(cluster):
    @serve.deployment(ray_actor_options={"num_cpus": 0.25})
    def double(x):
        return 2 * x

    handle = serve.run(double.bind())
    assert ray_tpu.get(handle.remote(8), timeout=60) == 16
    serve.delete("double")


def test_requests_spread_across_replicas(cluster):
    @serve.deployment(num_replicas=2, ray_actor_options={"num_cpus": 0.25})
    class WhoAmI:
        def __call__(self, _):
            import os

            return os.getpid()

    handle = serve.run(WhoAmI.bind())
    pids = set(
        ray_tpu.get([handle.remote(None) for _ in range(20)], timeout=120)
    )
    assert len(pids) == 2  # pow-2 routing reaches both replicas
    serve.delete("WhoAmI")


def test_replica_failure_recovery(cluster):
    @serve.deployment(num_replicas=2, ray_actor_options={"num_cpus": 0.25})
    class Flaky:
        def __call__(self, x):
            return x

    handle = serve.run(Flaky.bind())
    replicas = ray_tpu.get(
        handle._controller.get_replicas.remote("Flaky"), timeout=30
    )
    ray_tpu.kill(replicas[0])  # kill one replica
    # condition-based wait (controller-side long-poll on its change
    # condition) instead of client sleep-polling: returns the moment the
    # replacement replica is routed. 120s budget: replica respawn
    # includes a fresh worker cold-start, which can take well over 60s
    # on a box saturated by the full suite.
    st = ray_tpu.get(
        handle._controller.wait_status.remote(
            "Flaky", min_replicas=2, quiescent=True, timeout_s=120
        ),
        timeout=150,
    )
    assert st and st["replicas"] == 2, st
    # reconcile loop replaced the dead replica; traffic still flows.
    # Routing is at-most-once: a dispatch racing the replica death can
    # land on the dead actor, so allow a couple of retries.
    result = None
    for _ in range(3):
        try:
            result = ray_tpu.get(handle.remote(7), timeout=60)
            break
        except ray_tpu.RayTpuError:
            time.sleep(1.0)
    assert result == 7
    serve.delete("Flaky")


def test_autoscaling_up_and_down(cluster):
    @serve.deployment(
        ray_actor_options={"num_cpus": 0.1},
        max_concurrent_queries=4,
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=1,
            max_replicas=3,
            target_ongoing_requests=1.0,
            upscale_delay_s=0.1,
            downscale_delay_s=0.5,
        ),
    )
    class Slow:
        async def __call__(self, x):
            import asyncio

            await asyncio.sleep(1.0)
            return x

    handle = serve.run(Slow.bind())
    assert serve.status()["Slow"]["replicas"] == 1
    # sustained load so the autoscaler sees ongoing requests, then a
    # condition-based wait for the scale-up (controller-side long-poll
    # instead of client sleep-polling; the load thread keeps requests in
    # flight the whole time). 120s budget: scale-up = actor creation =
    # worker cold boot, which takes >60s when the suite saturates the box.
    import threading

    refs = []
    stop_load = threading.Event()

    def pump():
        while not stop_load.is_set():
            refs.extend(handle.remote(i) for i in range(4))
            stop_load.wait(0.4)

    loader = threading.Thread(target=pump, daemon=True)
    loader.start()
    try:
        st = ray_tpu.get(
            handle._controller.wait_status.remote(
                "Slow", min_replicas=2, timeout_s=120
            ),
            timeout=150,
        )
    finally:
        stop_load.set()
        loader.join(timeout=10)
    assert st and st["replicas"] >= 2, f"should scale up under load: {st}"
    ray_tpu.get(refs, timeout=120)
    # idle: scales back toward min (quiescent: the drain of the surplus
    # replica must have completed too)
    st = ray_tpu.get(
        handle._controller.wait_status.remote(
            "Slow", max_replicas=1, quiescent=True, timeout_s=90
        ),
        timeout=120,
    )
    assert st and st["replicas"] == 1, f"should scale down when idle: {st}"
    serve.delete("Slow")


def test_http_proxy(cluster):
    @serve.deployment(ray_actor_options={"num_cpus": 0.25}, route_prefix="/sq")
    class Square:
        def __call__(self, x):
            return x * x

    serve.run(Square.bind())
    from ray_tpu.serve.controller import get_or_create_controller

    serve.start_http(get_or_create_controller(), port=18114)
    req = urllib.request.Request(
        "http://127.0.0.1:18114/sq",
        data=json.dumps(7).encode(),
        headers={"Content-Type": "application/json"},
    )
    resp = json.loads(urllib.request.urlopen(req, timeout=60).read())
    assert resp["result"] == 49
    # unknown route -> 404
    try:
        urllib.request.urlopen("http://127.0.0.1:18114/nope", timeout=30)
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404
    serve.delete("Square")


def test_dynamic_batching(cluster):
    """@serve.batch: N concurrent requests coalesce into one replica
    call with a list argument (reference serve/batching.py)."""

    @serve.deployment(max_concurrent_queries=16)
    class Batcher:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.2)
        async def handle(self, items):
            self.batch_sizes.append(len(items))
            return [i * 10 for i in items]

        async def __call__(self, x):
            return await self.handle(x)

        async def sizes(self):
            return self.batch_sizes

    handle = serve.run(Batcher.bind(), name="batcher")
    refs = [handle.remote(i) for i in range(8)]
    assert sorted(ray_tpu.get(refs, timeout=60)) == [i * 10 for i in range(8)]
    sizes = ray_tpu.get(handle.method("sizes")(), timeout=30)
    # all 8 concurrent requests should land in few (ideally 1) batches
    assert max(sizes) >= 4, sizes
    assert sum(sizes) == 8, sizes
    serve.delete("Batcher")


def test_batching_error_propagates(cluster):
    @serve.deployment
    class Bad:
        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.05)
        async def handle(self, items):
            raise RuntimeError("batch boom")

        async def __call__(self, x):
            return await self.handle(x)

    handle = serve.run(Bad.bind(), name="bad")
    with pytest.raises(Exception, match="batch boom"):
        ray_tpu.get(handle.remote(1), timeout=60)
    serve.delete("Bad")


def test_rolling_update_zero_downtime(cluster):
    """Redeploying a new version rolls replicas start-before-kill: a
    request stream across the roll never fails, and answers flip to the
    new version (reference deployment_state.py:2331)."""

    @serve.deployment(num_replicas=2, version="v1")
    class Versioned:
        def __init__(self, tag):
            self.tag = tag

        def __call__(self, _x):
            return self.tag

    handle = serve.run(Versioned.bind("v1"), name="versioned")
    assert ray_tpu.get(handle.remote(0), timeout=60) == "v1"

    import threading

    results, errors = [], []
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                # retry-until-executed: the router re-chooses on a
                # death-raced dispatch, so the roll drops ZERO requests
                results.append(handle.call(0, _timeout=30))
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            time.sleep(0.02)

    t = threading.Thread(target=hammer)
    t.start()
    try:
        serve.run(
            Versioned.options(version="v2").bind("v2"), name="versioned"
        )
        # wait for the ROLL to finish (every routed replica on v2, none
        # starting/draining) via the controller's condition-based
        # long-poll — breaking on the first 'v2' response races a
        # legitimately-mixed routing set mid-roll (advisor finding r4)
        ray_tpu.get(
            handle._controller.wait_status.remote(
                "Versioned",
                min_replicas=2,
                quiescent=True,
                version="v2",
                timeout_s=60,
            ),
            timeout=90,
        )
        # a few post-roll requests must all answer v2
        post_roll = [handle.call(0, _timeout=30) for _ in range(3)]
    finally:
        stop.set()
        t.join(timeout=30)
    assert not errors, errors[:3]
    assert post_roll == ["v2"] * 3, post_roll
    assert "v1" in results  # the stream spanned the roll
    serve.delete("Versioned")


def test_same_version_redeploy_keeps_replicas(cluster):
    """Deploying the SAME version is an in-place config update — the
    running replicas survive (no churn)."""

    @serve.deployment(num_replicas=1, version="stable")
    class Stable:
        def __init__(self):
            import os

            self.pid = os.getpid()

        def __call__(self, _x):
            return self.pid

    handle = serve.run(Stable.bind(), name="stable")
    pid1 = ray_tpu.get(handle.remote(0), timeout=60)
    serve.run(Stable.bind(), name="stable")  # same version again
    time.sleep(1.0)
    pid2 = ray_tpu.get(handle.remote(0), timeout=60)
    assert pid1 == pid2
    serve.delete("Stable")


def test_streaming_deployment_handle(cluster):
    """Generator deployments stream values through handle.stream()
    (reference streaming replica responses)."""

    @serve.deployment(num_replicas=1, ray_actor_options={"num_cpus": 0.25})
    class Tokens:
        def __call__(self, prompt):
            for i, word in enumerate(str(prompt).split()):
                yield {"index": i, "token": word}

    handle = serve.run(Tokens.bind(), name="tokens")
    out = list(handle.stream("the quick brown fox"))
    assert [o["token"] for o in out] == ["the", "quick", "brown", "fox"]
    assert [o["index"] for o in out] == [0, 1, 2, 3]
    serve.delete("Tokens")


def test_streaming_async_deployment(cluster):
    @serve.deployment(num_replicas=1, ray_actor_options={"num_cpus": 0.25})
    class AsyncTokens:
        async def __call__(self, n):
            import asyncio as aio

            for i in range(n):
                await aio.sleep(0.01)
                yield f"t{i}"

    handle = serve.run(AsyncTokens.bind(), name="atokens")
    assert list(handle.stream(3)) == ["t0", "t1", "t2"]
    serve.delete("AsyncTokens")


def test_streaming_http_sse(cluster):
    """SSE through the HTTP proxy: Accept: text/event-stream gets one
    data: event per yielded item (reference proxy streaming)."""

    @serve.deployment(num_replicas=1, route_prefix="/sse", ray_actor_options={"num_cpus": 0.25})
    class SSE:
        def __call__(self, body):
            for i in range(3):
                yield {"n": i}

    serve.run(SSE.bind(), name="sse")
    from ray_tpu.serve.controller import get_or_create_controller

    # start_http is a per-process singleton: reuse whatever port it holds
    proxy = serve.start_http(get_or_create_controller(), port=18457)
    req = urllib.request.Request(
        f"http://127.0.0.1:{proxy.port}/sse",
        data=b"{}",
        headers={"Accept": "text/event-stream", "Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        assert resp.headers["Content-Type"].startswith("text/event-stream")
        raw = resp.read().decode()
    events = [
        json.loads(line[len("data: "):])
        for line in raw.splitlines()
        if line.startswith("data: ")
    ]
    assert events == [{"n": 0}, {"n": 1}, {"n": 2}], raw
    serve.delete("SSE")


def test_multiplexed_models_lru_eviction(cluster):
    """@serve.multiplexed: per-replica LRU of loaded models with
    eviction beyond max_num_models_per_replica (reference
    multiplex.py:22), model id carried by handle.options()."""

    @serve.deployment(num_replicas=1, ray_actor_options={"num_cpus": 0.25})
    class Multi:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=2)
        async def get_model(self, model_id: str):
            self.loads.append(model_id)
            return {"id": model_id, "scale": len(self.loads)}

        async def __call__(self, x):
            model = await self.get_model(serve.get_multiplexed_model_id())
            return {"model": model["id"], "x": x}

        def load_history(self):
            return self.loads

    handle = serve.run(Multi.bind(), name="multi")
    # three models through a capacity-2 cache
    for mid in ["m1", "m2", "m1", "m3", "m1"]:
        out = ray_tpu.get(
            handle.options(multiplexed_model_id=mid).remote(1), timeout=60
        )
        assert out["model"] == mid
    history = ray_tpu.get(handle.method("load_history")(), timeout=30)
    # m1: loaded once then cache-hit (still resident when m3 evicted m2)
    assert history == ["m1", "m2", "m3"], history
    # m2 was evicted; calling it again re-loads
    ray_tpu.get(handle.options(multiplexed_model_id="m2").remote(1), timeout=60)
    history = ray_tpu.get(handle.method("load_history")(), timeout=30)
    assert history == ["m1", "m2", "m3", "m2"], history
    serve.delete("Multi")


def test_multiplexed_model_aware_routing(cluster):
    """With multiple replicas, requests for a model prefer the replica
    that already loaded it (model-locality routing)."""
    import os

    @serve.deployment(num_replicas=2, ray_actor_options={"num_cpus": 0.25})
    class Which:
        @serve.multiplexed(max_num_models_per_replica=4)
        async def get_model(self, model_id: str):
            return model_id

        async def __call__(self, x):
            await self.get_model(serve.get_multiplexed_model_id())
            return os.getpid()

    handle = serve.run(Which.bind(), name="which")
    h = handle.options(multiplexed_model_id="modelA")
    first = h.call(0, _timeout=60)
    # subsequent calls for the same model land on the same replica
    # (stats TTL is 250ms — wait for a fresh stats fetch to pick up the
    # loaded-models set)
    time.sleep(0.4)
    pids = {h.call(0, _timeout=60) for _ in range(8)}
    assert pids == {first}, (first, pids)
    serve.delete("Which")


def test_dispatch_retry_on_replica_death(cluster):
    """handle.call() re-chooses when its dispatch races a replica kill
    (retry-until-executed; reference router)."""

    @serve.deployment(num_replicas=2, ray_actor_options={"num_cpus": 0.25})
    class Sturdy:
        def __call__(self, x):
            return x * 2

    handle = serve.run(Sturdy.bind(), name="sturdy")
    assert handle.call(4, _timeout=60) == 8
    # kill one replica out from under the router's cached set
    replicas = ray_tpu.get(
        handle._controller.get_replicas.remote("Sturdy"), timeout=30
    )
    ray_tpu.kill(replicas[0])
    # every call still succeeds (some will race the corpse and retry)
    for i in range(10):
        assert handle.call(i, _timeout=60) == i * 2
    serve.delete("Sturdy")
