"""ray_tpu.serve tests: deploy/route/scale/HTTP (reference test model:
``serve/tests/`` + ``_private/local_testing_mode.py``)."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_deploy_and_call(cluster):
    @serve.deployment(num_replicas=2, ray_actor_options={"num_cpus": 0.25})
    class Adder:
        def __init__(self, bias):
            self.bias = bias

        def __call__(self, x):
            return x + self.bias

        def bias_value(self):
            return self.bias

    handle = serve.run(Adder.bind(10))
    assert ray_tpu.get(handle.remote(5), timeout=60) == 15
    assert ray_tpu.get(handle.method("bias_value")(), timeout=30) == 10
    assert serve.status()["Adder"]["replicas"] == 2
    serve.delete("Adder")


def test_function_deployment(cluster):
    @serve.deployment(ray_actor_options={"num_cpus": 0.25})
    def double(x):
        return 2 * x

    handle = serve.run(double.bind())
    assert ray_tpu.get(handle.remote(8), timeout=60) == 16
    serve.delete("double")


def test_requests_spread_across_replicas(cluster):
    @serve.deployment(num_replicas=2, ray_actor_options={"num_cpus": 0.25})
    class WhoAmI:
        def __call__(self, _):
            import os

            return os.getpid()

    handle = serve.run(WhoAmI.bind())
    pids = set(
        ray_tpu.get([handle.remote(None) for _ in range(20)], timeout=120)
    )
    assert len(pids) == 2  # pow-2 routing reaches both replicas
    serve.delete("WhoAmI")


def test_replica_failure_recovery(cluster):
    @serve.deployment(num_replicas=2, ray_actor_options={"num_cpus": 0.25})
    class Flaky:
        def __call__(self, x):
            return x

    handle = serve.run(Flaky.bind())
    replicas = ray_tpu.get(
        handle._controller.get_replicas.remote("Flaky"), timeout=30
    )
    ray_tpu.kill(replicas[0])  # kill one replica
    deadline = time.time() + 60
    while time.time() < deadline:
        if serve.status()["Flaky"]["replicas"] == 2:
            break
        time.sleep(0.5)
    # reconcile loop replaced the dead replica; traffic still flows.
    # Routing is at-most-once: a dispatch racing the replica death can
    # land on the dead actor, so allow a couple of retries.
    result = None
    for _ in range(3):
        try:
            result = ray_tpu.get(handle.remote(7), timeout=60)
            break
        except ray_tpu.RayTpuError:
            time.sleep(1.0)
    assert result == 7
    assert serve.status()["Flaky"]["replicas"] == 2
    serve.delete("Flaky")


def test_autoscaling_up_and_down(cluster):
    @serve.deployment(
        ray_actor_options={"num_cpus": 0.1},
        max_concurrent_queries=4,
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=1,
            max_replicas=3,
            target_ongoing_requests=1.0,
            upscale_delay_s=0.1,
            downscale_delay_s=0.5,
        ),
    )
    class Slow:
        async def __call__(self, x):
            import asyncio

            await asyncio.sleep(1.0)
            return x

    handle = serve.run(Slow.bind())
    assert serve.status()["Slow"]["replicas"] == 1
    # sustained burst: keep requests in flight until the controller reacts
    # (generous window — CI shares one vCPU across the whole cluster)
    refs = []
    deadline = time.time() + 20
    scaled = False
    while time.time() < deadline:
        refs.extend(handle.remote(i) for i in range(4))
        time.sleep(0.4)
        if serve.status()["Slow"]["replicas"] >= 2:
            scaled = True
            break
    assert scaled, "should scale up under load"
    ray_tpu.get(refs, timeout=120)
    # idle: scales back toward min
    deadline = time.time() + 30
    while time.time() < deadline:
        if serve.status()["Slow"]["replicas"] == 1:
            break
        time.sleep(0.5)
    assert serve.status()["Slow"]["replicas"] == 1, "should scale down when idle"
    serve.delete("Slow")


def test_http_proxy(cluster):
    @serve.deployment(ray_actor_options={"num_cpus": 0.25}, route_prefix="/sq")
    class Square:
        def __call__(self, x):
            return x * x

    serve.run(Square.bind())
    from ray_tpu.serve.controller import get_or_create_controller

    serve.start_http(get_or_create_controller(), port=18114)
    req = urllib.request.Request(
        "http://127.0.0.1:18114/sq",
        data=json.dumps(7).encode(),
        headers={"Content-Type": "application/json"},
    )
    resp = json.loads(urllib.request.urlopen(req, timeout=60).read())
    assert resp["result"] == 49
    # unknown route -> 404
    try:
        urllib.request.urlopen("http://127.0.0.1:18114/nope", timeout=30)
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404
    serve.delete("Square")


def test_dynamic_batching(cluster):
    """@serve.batch: N concurrent requests coalesce into one replica
    call with a list argument (reference serve/batching.py)."""

    @serve.deployment(max_concurrent_queries=16)
    class Batcher:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.2)
        async def handle(self, items):
            self.batch_sizes.append(len(items))
            return [i * 10 for i in items]

        async def __call__(self, x):
            return await self.handle(x)

        async def sizes(self):
            return self.batch_sizes

    handle = serve.run(Batcher.bind(), name="batcher")
    refs = [handle.remote(i) for i in range(8)]
    assert sorted(ray_tpu.get(refs, timeout=60)) == [i * 10 for i in range(8)]
    sizes = ray_tpu.get(handle.method("sizes")(), timeout=30)
    # all 8 concurrent requests should land in few (ideally 1) batches
    assert max(sizes) >= 4, sizes
    assert sum(sizes) == 8, sizes
    serve.delete("Batcher")


def test_batching_error_propagates(cluster):
    @serve.deployment
    class Bad:
        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.05)
        async def handle(self, items):
            raise RuntimeError("batch boom")

        async def __call__(self, x):
            return await self.handle(x)

    handle = serve.run(Bad.bind(), name="bad")
    with pytest.raises(Exception, match="batch boom"):
        ray_tpu.get(handle.remote(1), timeout=60)
    serve.delete("Bad")


def test_rolling_update_zero_downtime(cluster):
    """Redeploying a new version rolls replicas start-before-kill: a
    request stream across the roll never fails, and answers flip to the
    new version (reference deployment_state.py:2331)."""

    @serve.deployment(num_replicas=2, version="v1")
    class Versioned:
        def __init__(self, tag):
            self.tag = tag

        def __call__(self, _x):
            return self.tag

    handle = serve.run(Versioned.bind("v1"), name="versioned")
    assert ray_tpu.get(handle.remote(0), timeout=60) == "v1"

    import threading

    results, errors = [], []
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                results.append(ray_tpu.get(handle.remote(0), timeout=30))
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            time.sleep(0.02)

    t = threading.Thread(target=hammer)
    t.start()
    try:
        serve.run(
            Versioned.options(version="v2").bind("v2"), name="versioned"
        )
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if results and results[-1] == "v2":
                break
            time.sleep(0.2)
    finally:
        stop.set()
        t.join(timeout=30)
    assert not errors, errors[:3]
    assert results[-1] == "v2", results[-5:]
    assert "v1" in results  # the stream spanned the roll
    serve.delete("Versioned")


def test_same_version_redeploy_keeps_replicas(cluster):
    """Deploying the SAME version is an in-place config update — the
    running replicas survive (no churn)."""

    @serve.deployment(num_replicas=1, version="stable")
    class Stable:
        def __init__(self):
            import os

            self.pid = os.getpid()

        def __call__(self, _x):
            return self.pid

    handle = serve.run(Stable.bind(), name="stable")
    pid1 = ray_tpu.get(handle.remote(0), timeout=60)
    serve.run(Stable.bind(), name="stable")  # same version again
    time.sleep(1.0)
    pid2 = ray_tpu.get(handle.remote(0), timeout=60)
    assert pid1 == pid2
    serve.delete("Stable")
