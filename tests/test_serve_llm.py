"""E2E: the LLM inference engine behind Serve (ISSUE 4 acceptance).

A toy-Llama deployment on the simulated cluster must handle >= 8
concurrent streaming generation requests with continuous batching
observably active, zero post-warmup recompiles, and engine metrics
visible on /metrics."""

import threading
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve

pytest.importorskip("jax")

from ray_tpu.inference.engine import EngineConfig  # noqa: E402
from ray_tpu.models.llama import LlamaConfig  # noqa: E402


@pytest.fixture(scope="module")
def llm_handle():
    ray_tpu.init(num_cpus=4)
    cfg = LlamaConfig.tiny()
    ec = EngineConfig(
        num_blocks=64, block_size=8, prefill_buckets=(8, 16, 32),
        decode_buckets=(1, 2, 4, 8), max_decode_batch=8,
        max_new_tokens_default=8,
    )
    dep = serve.llm_deployment(
        cfg, engine=ec, num_replicas=1, ray_actor_options={"num_cpus": 0.5}
    )
    handle = serve.run(dep.bind())
    yield handle
    serve.shutdown()
    ray_tpu.shutdown()


def test_concurrent_streaming_with_continuous_batching(llm_handle):
    n = 8
    results = {}
    errors = {}

    def consume(i):
        try:
            results[i] = list(
                llm_handle.stream(
                    {"prompt": [1 + i, 2, 3, 4 + i], "max_new_tokens": 12},
                    _method="generate",
                    _timeout=120,
                )
            )
        except Exception as e:  # noqa: BLE001
            errors[i] = e

    threads = [threading.Thread(target=consume, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    assert len(results) == n
    assert all(len(v) == 12 for v in results.values())
    # determinism cross-check: same prompt twice -> same greedy stream
    again = list(
        llm_handle.stream(
            {"prompt": [1, 2, 3, 4], "max_new_tokens": 12},
            _method="generate",
            _timeout=120,
        )
    )
    assert again == results[0]

    stats = ray_tpu.get(llm_handle.method("engine_stats")(), timeout=60)
    sched = stats["scheduler"]
    # continuous batching observably active: a decode batch > 1 ran, and
    # at least one step decoded while a later request was prefilling
    assert sched["max_decode_batch_seen"] > 1, sched
    assert sched["steps_with_prefill_and_decode"] > 0, sched
    # fixed-shape buckets: zero recompiles beyond the bucket programs
    assert stats["recompiles_after_warmup"] == 0
    # prefill + decode buckets + the COW block-copy program
    assert stats["compile_count"] == 3 + 4 + 1
    # all KV blocks returned after the burst
    assert stats["blocks"]["used_blocks"] == 0


def test_metrics_visible_on_metrics_endpoint(llm_handle):
    # (fires after the streaming test -> counters are warm)
    addr = ray_tpu.get(llm_handle.method("metrics_address")(), timeout=60)
    assert addr, "replica did not start a metrics endpoint"
    body = urllib.request.urlopen(f"http://{addr}/metrics", timeout=10).read().decode()
    for needle in (
        "raytpu_llm_ttft_seconds",
        "raytpu_llm_tokens_per_s",
        "raytpu_llm_kv_cache_utilization",
        "raytpu_llm_queue_depth",
        "raytpu_llm_tokens_generated_total",
    ):
        assert needle in body, f"{needle} missing from /metrics"


def test_nonstreaming_call_and_deadline_budget(llm_handle):
    out = ray_tpu.get(
        llm_handle.remote({"prompt": [5, 6, 7], "max_new_tokens": 4}), timeout=120
    )
    assert len(out["tokens"]) == 4
    # the caller's deadline propagates to the replica: an already-spent
    # budget fails the generation instead of decoding for a dead caller
    with pytest.raises(Exception):
        with ray_tpu.deadline_scope(0.0):
            ray_tpu.get(
                llm_handle.remote({"prompt": [5, 6, 7], "max_new_tokens": 4}),
                timeout=30,
            )


def test_drain_finishes_in_flight_streams_zero_errors(llm_handle):
    """Engine drain mid-decode: in-flight streams complete cleanly, new
    submissions are refused until the drain flag clears (fresh replicas
    created by serve recovery/rollouts start undrained)."""
    n = 4
    results = {}
    errors = {}
    started = threading.Barrier(n + 1, timeout=60)

    def consume(i):
        try:
            gen = llm_handle.stream(
                {"prompt": [2 + i, 3, 5], "max_new_tokens": 40},
                _method="generate",
                _timeout=120,
            )
            it = iter(gen)
            first = next(it)
            started.wait()  # streams live -> main thread drains
            results[i] = [first] + list(it)
        except Exception as e:  # noqa: BLE001
            errors[i] = e
            try:
                started.wait()
            except Exception:
                pass

    threads = [threading.Thread(target=consume, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    started.wait()  # every stream has produced >= 1 token
    ray_tpu.get(llm_handle.method("begin_drain")(30.0), timeout=60)
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    assert all(len(v) == 40 for v in results.values()), {
        k: len(v) for k, v in results.items()
    }
    stats = ray_tpu.get(llm_handle.method("engine_stats")(), timeout=60)
    assert stats["draining"] is True
    assert stats["scheduler"]["running"] == 0
    assert stats["blocks"]["used_blocks"] == 0


def test_multi_replica_affinity_routing_and_replica_death(llm_handle):
    """Multi-replica scale-out E2E (ISSUE 7): a 2-replica deployment with
    cache-affinity routing pins same-prefix streams to the prefix-warm
    replica; killing the OTHER replica mid-stream leaves every live
    stream to finish with zero client-visible errors, and the controller
    replaces the dead replica."""
    import time

    from ray_tpu.core.config import GLOBAL_CONFIG
    from ray_tpu.inference.engine import EngineConfig
    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.observability.rpc_metrics import ROUTER_AFFINITY_HITS

    ec = EngineConfig(
        num_blocks=64, block_size=8, prefill_buckets=(8, 32),
        decode_buckets=(1, 4), max_decode_batch=4, max_new_tokens_default=8,
    )
    dep = serve.llm_deployment(
        LlamaConfig.tiny(), engine=ec, name="llm2", num_replicas=2,
        route_prefix="/llm2", ray_actor_options={"num_cpus": 0.25},
    )
    handle = serve.run(dep.bind())
    old_weight = GLOBAL_CONFIG.serve_affinity_weight
    # pin hard: affinity must beat the optimistic load bumps so every
    # warm-prefix stream deterministically lands on the warm replica
    GLOBAL_CONFIG.serve_affinity_weight = 1e6
    try:
        ctrl = ray_tpu.get_actor("__serve_controller__")
        ray_tpu.get(
            ctrl.wait_status.remote("llm2", min_replicas=2, timeout_s=60),
            timeout=90,
        )
        prompt = [11, 3, 7, 5, 2, 9, 8, 6] * 3  # 24 tokens = 3 full blocks
        warm = list(handle.stream(
            {"prompt": prompt + [42], "max_new_tokens": 4},
            _method="generate", _timeout=120,
        ))
        assert len(warm) == 4
        # let both replicas' gossip (incl. the fresh prefix digest) reach
        # the router so the scored path engages for every stream below
        deadline = time.monotonic() + 20
        warm_replica = cold_replica = None
        while time.monotonic() < deadline:
            replicas = ray_tpu.get(ctrl.get_replicas.remote("llm2"), timeout=30)
            stats = [
                ray_tpu.get(
                    r.handle_request.remote("engine_stats", [], {}, ""),
                    timeout=60,
                )
                for r in replicas
            ]
            hot = [
                r for r, s in zip(replicas, stats)
                if s["scheduler"]["total_admitted"] > 0
            ]
            cold = [
                r for r, s in zip(replicas, stats)
                if s["scheduler"]["total_admitted"] == 0
            ]
            if len(replicas) == 2 and len(hot) == 1 and len(cold) == 1:
                warm_replica, cold_replica = hot[0], cold[0]
                break
            time.sleep(0.25)
        assert warm_replica is not None, "could not identify the warm replica"
        time.sleep(3 * GLOBAL_CONFIG.serve_replica_stats_period_s)

        hits_before = ROUTER_AFFINITY_HITS._values.get(("llm2",), 0.0)
        n = 4
        results, errors = {}, {}
        started = threading.Barrier(n + 1, timeout=60)

        def consume(i):
            try:
                gen = handle.stream(
                    {"prompt": prompt + [60 + i], "max_new_tokens": 30},
                    _method="generate", _timeout=120,
                )
                it = iter(gen)
                first = next(it)
                started.wait()  # all streams live -> main thread kills
                results[i] = [first] + list(it)
            except Exception as e:  # noqa: BLE001
                errors[i] = e
                try:
                    started.wait()
                except Exception:
                    pass

        threads = [threading.Thread(target=consume, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        started.wait()  # every stream produced >= 1 token
        # kill the replica the affinity router did NOT pick: live streams
        # ride the warm replica and must all finish untouched
        ray_tpu.kill(cold_replica)
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        assert all(len(v) == 30 for v in results.values()), {
            k: len(v) for k, v in results.items()
        }
        # affinity routing provably engaged (scored decisions with a
        # prefix-warm winner) and the warm replica actually reused blocks
        assert ROUTER_AFFINITY_HITS._values.get(("llm2",), 0.0) > hits_before
        warm_stats = ray_tpu.get(
            warm_replica.handle_request.remote("engine_stats", [], {}, ""),
            timeout=60,
        )
        assert warm_stats["prefix_cache"]["hits_total"] >= n
        assert warm_stats["prefix_cache"]["tokens_saved_total"] >= n * 24
        # the controller replaces the killed replica (start-before-kill
        # machinery from the drain/failover PRs)
        st = ray_tpu.get(
            ctrl.wait_status.remote("llm2", min_replicas=2, timeout_s=90),
            timeout=120,
        )
        assert st["replicas"] == 2, st
        # and the deployment still answers (fresh replica included)
        again = list(handle.stream(
            {"prompt": prompt + [42], "max_new_tokens": 4},
            _method="generate", _timeout=120,
        ))
        assert again == warm
    finally:
        GLOBAL_CONFIG.serve_affinity_weight = old_weight
        serve.delete("llm2")
