"""E2E: the LLM inference engine behind Serve (ISSUE 4 acceptance).

A toy-Llama deployment on the simulated cluster must handle >= 8
concurrent streaming generation requests with continuous batching
observably active, zero post-warmup recompiles, and engine metrics
visible on /metrics."""

import threading
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve

pytest.importorskip("jax")

from ray_tpu.inference.engine import EngineConfig  # noqa: E402
from ray_tpu.models.llama import LlamaConfig  # noqa: E402


@pytest.fixture(scope="module")
def llm_handle():
    ray_tpu.init(num_cpus=4)
    cfg = LlamaConfig.tiny()
    ec = EngineConfig(
        num_blocks=64, block_size=8, prefill_buckets=(8, 16, 32),
        decode_buckets=(1, 2, 4, 8), max_decode_batch=8,
        max_new_tokens_default=8,
    )
    dep = serve.llm_deployment(
        cfg, engine=ec, num_replicas=1, ray_actor_options={"num_cpus": 0.5}
    )
    handle = serve.run(dep.bind())
    yield handle
    serve.shutdown()
    ray_tpu.shutdown()


def test_concurrent_streaming_with_continuous_batching(llm_handle):
    n = 8
    results = {}
    errors = {}

    def consume(i):
        try:
            results[i] = list(
                llm_handle.stream(
                    {"prompt": [1 + i, 2, 3, 4 + i], "max_new_tokens": 12},
                    _method="generate",
                    _timeout=120,
                )
            )
        except Exception as e:  # noqa: BLE001
            errors[i] = e

    threads = [threading.Thread(target=consume, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    assert len(results) == n
    assert all(len(v) == 12 for v in results.values())
    # determinism cross-check: same prompt twice -> same greedy stream
    again = list(
        llm_handle.stream(
            {"prompt": [1, 2, 3, 4], "max_new_tokens": 12},
            _method="generate",
            _timeout=120,
        )
    )
    assert again == results[0]

    stats = ray_tpu.get(llm_handle.method("engine_stats")(), timeout=60)
    sched = stats["scheduler"]
    # continuous batching observably active: a decode batch > 1 ran, and
    # at least one step decoded while a later request was prefilling
    assert sched["max_decode_batch_seen"] > 1, sched
    assert sched["steps_with_prefill_and_decode"] > 0, sched
    # fixed-shape buckets: zero recompiles beyond the bucket programs
    assert stats["recompiles_after_warmup"] == 0
    assert stats["compile_count"] == 3 + 4  # prefill + decode buckets
    # all KV blocks returned after the burst
    assert stats["blocks"]["used_blocks"] == 0


def test_metrics_visible_on_metrics_endpoint(llm_handle):
    # (fires after the streaming test -> counters are warm)
    addr = ray_tpu.get(llm_handle.method("metrics_address")(), timeout=60)
    assert addr, "replica did not start a metrics endpoint"
    body = urllib.request.urlopen(f"http://{addr}/metrics", timeout=10).read().decode()
    for needle in (
        "raytpu_llm_ttft_seconds",
        "raytpu_llm_tokens_per_s",
        "raytpu_llm_kv_cache_utilization",
        "raytpu_llm_queue_depth",
        "raytpu_llm_tokens_generated_total",
    ):
        assert needle in body, f"{needle} missing from /metrics"


def test_nonstreaming_call_and_deadline_budget(llm_handle):
    out = ray_tpu.get(
        llm_handle.remote({"prompt": [5, 6, 7], "max_new_tokens": 4}), timeout=120
    )
    assert len(out["tokens"]) == 4
    # the caller's deadline propagates to the replica: an already-spent
    # budget fails the generation instead of decoding for a dead caller
    with pytest.raises(Exception):
        with ray_tpu.deadline_scope(0.0):
            ray_tpu.get(
                llm_handle.remote({"prompt": [5, 6, 7], "max_new_tokens": 4}),
                timeout=30,
            )


def test_drain_finishes_in_flight_streams_zero_errors(llm_handle):
    """Engine drain mid-decode: in-flight streams complete cleanly, new
    submissions are refused until the drain flag clears (fresh replicas
    created by serve recovery/rollouts start undrained)."""
    n = 4
    results = {}
    errors = {}
    started = threading.Barrier(n + 1, timeout=60)

    def consume(i):
        try:
            gen = llm_handle.stream(
                {"prompt": [2 + i, 3, 5], "max_new_tokens": 40},
                _method="generate",
                _timeout=120,
            )
            it = iter(gen)
            first = next(it)
            started.wait()  # streams live -> main thread drains
            results[i] = [first] + list(it)
        except Exception as e:  # noqa: BLE001
            errors[i] = e
            try:
                started.wait()
            except Exception:
                pass

    threads = [threading.Thread(target=consume, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    started.wait()  # every stream has produced >= 1 token
    ray_tpu.get(llm_handle.method("begin_drain")(30.0), timeout=60)
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    assert all(len(v) == 40 for v in results.values()), {
        k: len(v) for k, v in results.items()
    }
    stats = ray_tpu.get(llm_handle.method("engine_stats")(), timeout=60)
    assert stats["draining"] is True
    assert stats["scheduler"]["running"] == 0
    assert stats["blocks"]["used_blocks"] == 0
