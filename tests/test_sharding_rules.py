"""Unified partition rules end-to-end (ISSUE 14).

``match_partition_rules`` units, numerics parity of the constrained
fwd/bwd/optimizer step against the unconstrained single-chip reference,
zero post-warmup recompiles for the constrained step, backward-block
parity against the XLA attention grad, and the involuntary-remat
tripwire's stderr capture. All pure-jax on the virtual CPU mesh — no
cluster, no warmup (tier-1 CAUTION: the suite saturates its cap)."""

import importlib.util
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from ray_tpu.models.llama import (
    LlamaConfig,
    batch_sharding,
    init_params,
    make_train_step,
    next_token_loss,
    param_shardings,
    partition_rules,
)
from ray_tpu.parallel.mesh import MeshSpec, cpu_mesh_devices, make_mesh
from ray_tpu.parallel.sharding import (
    match_partition_rules,
    tp_rules,
    tree_path_names,
)


# -- match_partition_rules units ------------------------------------------


def test_match_rules_scalar_skip_and_match():
    tree = {
        "layers": [{"wq": np.zeros((4, 8)), "count": np.zeros(())}],
        "one": np.zeros((1,)),
    }
    specs = match_partition_rules([(r"wq$", P("fsdp", "tensor"))], tree)
    assert specs["layers"][0]["wq"] == P("fsdp", "tensor")
    # scalar and single-element leaves never consult the rules
    assert specs["layers"][0]["count"] == P()
    assert specs["one"] == P()


def test_match_rules_no_rule_found_raises():
    with pytest.raises(ValueError, match="no partition rule"):
        match_partition_rules([(r"wq$", P())], {"wz": np.zeros((4, 4))})


def test_match_rules_override_precedence_first_wins():
    tree = {"a": {"wq": np.zeros((4, 8))}, "b": {"wq": np.zeros((4, 8))}}
    # override in FRONT: the targeted path diverges, the generic rule
    # still covers the rest
    specs = match_partition_rules(
        [(r"a/wq$", P("tensor", None)), (r"wq$", P("fsdp", None))], tree
    )
    assert specs["a"]["wq"] == P("tensor", None)
    assert specs["b"]["wq"] == P("fsdp", None)
    # generic rule first: it shadows the targeted one entirely
    specs = match_partition_rules(
        [(r"wq$", P("fsdp", None)), (r"a/wq$", P("tensor", None))], tree
    )
    assert specs["a"]["wq"] == P("fsdp", None)


def test_match_rules_rank_reduced_leaf_replicates():
    """A matched spec LONGER than the leaf's rank (adafactor v_row/v_col,
    SM3 diagonals — rank-reduced mirrors named after their 2-D param)
    replicates instead of raising or mis-applying the param's spec."""
    tree = {"v_row": {"wq": np.zeros((8,))}, "full": {"wq": np.zeros((8, 4))}}
    specs = match_partition_rules([(r"wq$", P("fsdp", "tensor"))], tree)
    assert specs["v_row"]["wq"] == P()
    assert specs["full"]["wq"] == P("fsdp", "tensor")


def test_init_sharded_factored_optimizer_state():
    """init_sharded survives a rank-reducing optimizer: factored adafactor
    stats don't mirror param shapes, so the suffix-matched param spec is
    inapplicable to them — they init replicated and the constrained step
    still runs (the reproduction from the ISSUE-14 review pass)."""
    from ray_tpu.models.llama import init_sharded

    cfg = LlamaConfig.tiny()
    mesh = make_mesh(MeshSpec(data=2, fsdp=2, tensor=2), cpu_mesh_devices(8))
    opt = optax.adafactor(1e-3, min_dim_size_to_factor=2)

    # the v_(row|col) NAME rule replicates every factored stat — the
    # rank-length backstop alone can't: wq's stripped rank-2 spec would
    # otherwise "fit" its rank-2 v_row and shard the wrong dims
    specs = match_partition_rules(
        partition_rules(cfg, tp_rules()), opt.init(init_params(cfg, jax.random.PRNGKey(0)))
    )
    names = tree_path_names(specs)
    factored = {
        n: s
        for n, s in zip(names, jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, P)))
        if "/v_row/" in n or "/v_col/" in n
    }
    assert factored and all(s == P() for s in factored.values()), factored

    params, opt_state = init_sharded(
        cfg, mesh, tp_rules(), jax.random.PRNGKey(0), opt
    )
    # same-seed parity of sharded init vs the eager single-chip
    # reference: both run partitionable threefry, so values are
    # bit-identical whatever the mesh
    ref = init_params(cfg, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(
        np.asarray(params["embed"]), np.asarray(ref["embed"])
    )
    step = make_train_step(
        cfg, opt, donate=False, mesh=mesh, rules=tp_rules(), remat="selective"
    )
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size, jnp.int32
    )
    batch = jax.device_put(
        {"tokens": tokens, "targets": tokens}, batch_sharding(mesh, tp_rules())
    )
    (_, _), loss = step((params, opt_state), batch)
    assert np.isfinite(float(loss))


def test_llama_rules_cover_params_grads_and_opt_state():
    """One regex table covers the param tree AND the optax state (mu/nu
    mirror params, so the same suffixes match; scalar count is skipped)."""
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    opt_state = optax.adamw(1e-3).init(params)
    prules = partition_rules(cfg, tp_rules())
    specs_p = match_partition_rules(prules, params)  # raises on any gap
    specs_o = match_partition_rules(prules, opt_state)
    # the mirrored wq leaf landed on the identical spec
    names = tree_path_names(specs_o)
    leaves = jax.tree_util.tree_leaves(
        specs_o, is_leaf=lambda x: isinstance(x, P)
    )
    wq_specs = {n: s for n, s in zip(names, leaves) if n.endswith("wq")}
    assert wq_specs, names[:8]
    for spec in wq_specs.values():
        assert spec == specs_p["layers"][0]["wq"]


# -- constrained step: numerics parity + zero recompiles ------------------


def test_constrained_step_matches_unconstrained_reference():
    """The unified (rules-constrained, selective-remat) step on the 8-dev
    CPU mesh produces the same losses as the unconstrained single-device
    step on identical params/batch — the constraints move shardings, not
    values. Also asserts zero post-warmup recompiles for the constrained
    step (the jit cache stays at one entry across repeat steps)."""
    cfg = LlamaConfig.tiny()
    opt = optax.adamw(1e-3)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size, jnp.int32
    )
    batch = {"tokens": tokens, "targets": tokens}

    ref_step = make_train_step(cfg, opt, donate=False)
    ref_state = (params, opt.init(params))
    ref_losses = []
    for _ in range(3):
        ref_state, loss = ref_step(ref_state, batch)
        ref_losses.append(float(loss))

    mesh = make_mesh(MeshSpec(data=2, fsdp=2, tensor=2), cpu_mesh_devices(8))
    rules = tp_rules()
    sharded = jax.device_put(params, param_shardings(cfg, mesh, rules))
    bd = jax.device_put(batch, batch_sharding(mesh, rules))
    con_step = make_train_step(
        cfg, opt, donate=False, mesh=mesh, rules=rules, remat="selective"
    )
    # optimizer state pinned to the same matched table the step emits —
    # the zero-recompile assertion below depends on it
    from jax.sharding import NamedSharding

    ospecs = match_partition_rules(partition_rules(cfg, rules), opt.init(params))
    con_opt = jax.device_put(
        opt.init(params),
        jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), ospecs,
            is_leaf=lambda x: isinstance(x, P),
        ),
    )
    con_state = (sharded, con_opt)
    con_losses = []
    for _ in range(3):
        con_state, loss = con_step(con_state, bd)
        con_losses.append(float(loss))

    np.testing.assert_allclose(ref_losses, con_losses, rtol=2e-4)
    size = getattr(con_step, "_cache_size", None)
    if size is not None:
        assert size() == 1, (
            f"constrained step recompiled after warmup: {size()} cache entries"
        )


def test_selective_remat_matches_no_remat():
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(
        jax.random.PRNGKey(2), (2, 16), 0, cfg.vocab_size
    )
    l0 = next_token_loss(cfg, params, tokens, tokens, remat=False)
    l1 = next_token_loss(cfg, params, tokens, tokens, remat="selective")
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-5)


def test_remat_rejects_unknown_mode():
    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.zeros((1, 16), jnp.int32)
    with pytest.raises(ValueError, match="remat"):
        next_token_loss(cfg, params, tokens, tokens, remat="bogus")


# -- backward block tuning ------------------------------------------------


def test_backward_blocks_parity_vs_xla_grad():
    """The Pallas backward running DIFFERENT (tuned) blocks than the
    forward still matches the XLA attention gradient, GQA included."""
    from ray_tpu.ops.attention import flash_attention, reference_attention

    b, h, hk, s, d = 1, 4, 2, 256, 32
    q = jax.random.normal(jax.random.PRNGKey(0), (b, h, s, d))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, hk, s, d))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, hk, s, d))

    def loss_flash(q, k, v):
        out = flash_attention(
            q, k, v, causal=True, impl="pallas",
            block_q=128, block_k=128, block_q_bwd=256, block_k_bwd=128,
        )
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        rep = h // hk
        out = reference_attention(
            q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1),
            causal=True,
        )
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b2 in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b2), atol=5e-5)


def test_default_bwd_blocks_bucket_table():
    from ray_tpu.ops.attention import default_bwd_blocks

    assert default_bwd_blocks(512) == (256, 512)
    assert default_bwd_blocks(2048) == (256, 1024)
    assert default_bwd_blocks(16384) == (128, 1024)
    # every bucket choice divides its bucket bound (usable as-is)
    for bound, (bq, bk) in [(1024, default_bwd_blocks(1024)),
                            (2048, default_bwd_blocks(2048)),
                            (8192, default_bwd_blocks(8192))]:
        assert bound % bq == 0 and bound % bk == 0


# -- involuntary-remat tripwire -------------------------------------------


def test_tripwire_capture_counts_and_replays():
    """The dryrun's fd-level stderr capture counts involuntary-remat
    lines written by C++ (bypassing sys.stderr) and replays the bytes."""
    spec = importlib.util.spec_from_file_location(
        "_graft_entry_for_test",
        os.path.join(os.path.dirname(__file__), "..", "__graft_entry__.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    import sys

    # write to whatever fd sys.stderr maps to (pytest's fd capture
    # remaps it; in the real dryrun it IS fd 2 — where XLA's C++ writes)
    fd = sys.stderr.fileno()
    counts: list = []
    with mod._capture_xla_stderr(counts):
        os.write(
            fd,
            b"W0000 [SPMD] Involuntary full rematerialization. blah\n"
            b"other line\n"
            b"E0000 [spmd] Involuntary full rematerialization. again\n",
        )
    assert counts == [2]
    counts2: list = []
    with mod._capture_xla_stderr(counts2):
        os.write(fd, b"nothing to see\n")
    assert counts2 == [0]
