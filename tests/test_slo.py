"""ISSUE 15: the SLO ledger — aggregatable log-bucket latency
histograms, exact intake-conservation books, and the flight recorder.

Cluster-free by design (the ROADMAP PR-13 caution: the tier-1 suite
saturates its budget): the cross-process report path is gated by the
assertions added to the existing chaos E2Es (`test_stream_resume.py`
resumed-stream ledger + `test_ingress.py` ingress books), so nothing
here spins a cluster or compiles a warmup bucket.
"""

import threading
import time

import pytest

from ray_tpu.observability import slo
from ray_tpu.observability.metrics import Histogram, bucket_quantile


def test_log_buckets_resolve_p999_within_five_percent():
    """The whole point of fixed log buckets: ANY quantile — p99.9 of a
    cluster-wide merged distribution included — interpolates from
    summed counts at ~(ratio-1)/2 relative error. Quantile gauges can
    never be merged; bucket counts sum exactly."""
    import random

    rng = random.Random(7)
    vals = [rng.lognormvariate(-3.0, 1.2) for _ in range(30_000)]
    # split the samples across two "processes", merge the counts
    a, b = slo.BucketCounts(), slo.BucketCounts()
    for i, v in enumerate(vals):
        (a if i % 2 else b).observe(v)
    merged = slo.BucketCounts().merge(a).merge(b)
    assert merged.total == len(vals)
    ordered = sorted(vals)
    for q in (0.50, 0.99, 0.999):
        est = merged.quantile(q)
        exact = ordered[int(q * len(ordered)) - 1]
        assert abs(est - exact) / exact < 0.06, (q, est, exact)
    # merge == observing everything in one process (counts are exact)
    whole = slo.BucketCounts()
    for v in vals:
        whole.observe(v)
    assert whole.counts == merged.counts
    # the registry Histogram agrees with the tape on the same buckets
    h = Histogram("rtslo_selftest_seconds", "t", buckets=slo.SLO_BUCKETS)
    for v in vals[:1000]:
        h.observe(v)
    tape = slo.BucketCounts()
    for v in vals[:1000]:
        tape.observe(v)
    ent = h.counts()
    assert ent[: len(slo.SLO_BUCKETS) + 1] == tape.counts
    assert h.quantiles((0.5,))[0.5] == tape.quantile(0.5)
    # empty histogram → None, never a crash
    assert bucket_quantile(slo.SLO_BUCKETS, [0] * (len(slo.SLO_BUCKETS) + 1), 0.99) is None


def test_flight_recorder_bounded_slowest_k_and_flagged_retention():
    fr = slo.FlightRecorder(slow_slots=16, flagged_slots=32)
    for i in range(5000):
        fr.add(
            {"request_id": f"r{i}", "e2e_s": float(i)},
            flagged=(i % 100 == 0),
            slow_key=float(i),
        )
    snap = fr.snapshot()
    # bounded: at most flagged ring + slowest-K survive
    assert len(snap) <= 16 + 32
    # the slowest requests are exactly the retained heap
    slow = sorted(e["e2e_s"] for e in snap if int(e["e2e_s"]) % 100 != 0)
    assert slow[-1] == 4999.0 and len([s for s in slow if s >= 4984]) >= 15
    # flagged entries survive regardless of their latency (newest win)
    assert any(e["e2e_s"] == 4900.0 for e in snap)
    assert fr.added == 5000


def test_books_balanced_identities():
    assert slo.books_balanced(
        {"kind": "engine", "submitted": 7, "finished": 3, "failed": 2,
         "cancelled": 1, "queued": 1, "running": 0}
    )
    assert not slo.books_balanced(
        {"kind": "engine", "submitted": 7, "finished": 3, "failed": 2,
         "cancelled": 1, "queued": 0, "running": 0}
    )
    assert slo.books_balanced(
        {"kind": "ingress", "seen": 5, "shed": 2, "bad_request": 1, "forwarded": 2}
    )
    assert not slo.books_balanced({"kind": "mystery"})


def test_report_joins_flight_entries_across_tiers_by_base_request_id():
    """A resumed request leaves one router-tier entry (rid) and several
    engine-tier entries (rid, rid.r1, ...); the report must fold them
    into ONE record whose stage map names the failover stage."""
    router_entry = {
        "tier": "router", "request_id": "abc123", "deployment": "llm",
        "tenant_class": "interactive", "trace_id": "t1", "resumes": 1,
        "replayed_tokens": 5, "ttft_s": 0.05, "e2e_s": 2.0,
        "stages": {"failover": 1.5}, "flags": ["resumed"], "outcome": "ok",
    }
    engine_a = {
        "tier": "engine", "request_id": "abc123", "deployment": "llm",
        "outcome": "failed", "stages": {"queue": 0.01, "prefill": 0.2},
        "e2e_s": 0.5,
    }
    engine_b = {
        "tier": "engine", "request_id": "abc123.r1", "deployment": "llm",
        "outcome": "finished", "stages": {"queue": 0.02, "decode": 0.3},
        "e2e_s": 0.6,
    }
    rep = slo.build_report(
        [{"flight": [engine_a, engine_b], "histograms": {}, "counters": {}},
         {"flight": [router_entry], "histograms": {}, "counters": {}}]
    )
    recs = rep["flight_recorder"]
    assert len(recs) == 1, recs
    rec = recs[0]
    assert rec["request_id"] == "abc123"
    # the tier closest to the client decides the joined outcome: the
    # router delivered the full stream, so attempt 0's engine 'failed'
    # must not label the record (regardless of snapshot order)
    assert rec["outcome"] == "ok", rec
    assert "_outcome_rank" not in rec
    assert rec["trace_id"] == "t1" and rec["resumes"] == 1
    assert rec["stages"]["router.failover"] == 1.5
    assert rec["stages"]["engine.queue"] == 0.02  # max across attempts
    assert rec["slowest_stage"] == "router.failover"
    assert "engine" in rec["tiers"] and "router" in rec["tiers"]


def test_engine_ledger_books_and_stage_breakdown(monkeypatch):
    """Engine-tier conservation: a mix of clean finishes, a mid-stream
    cancel, and a deadline expiry must leave submitted == finished +
    failed + cancelled exactly (nothing in flight), with the finished
    request's flight entry carrying the queue/prefill/decode stage
    breakdown and the class label. warmup=False + minimal buckets per
    the ROADMAP suite-budget caution."""
    jax = pytest.importorskip("jax")
    from ray_tpu.inference.engine import EngineConfig, InferenceEngine
    from ray_tpu.models.llama import LlamaConfig, init_params

    # the flight recorder is process-global: earlier driver-local engine
    # tests in the same pytest process left entries (and their cold-start
    # TTFTs could evict this test's fast finish from the slowest-K heap)
    # — swap in a fresh ring for the duration of this test
    monkeypatch.setattr(slo, "_RECORDER", slo.FlightRecorder())

    cfg = LlamaConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    ec = EngineConfig(
        num_blocks=64, block_size=8, prefill_buckets=(8,),
        decode_buckets=(1, 2), max_decode_batch=2, warmup=False,
    )
    eng = InferenceEngine(cfg, params, ec).start()
    eng.set_deployment_name("slotest")
    try:
        toks = list(eng.generate(
            [1, 2, 3], max_new_tokens=6, tenant_class="interactive"
        ))
        assert len(toks) == 6
        # cancel mid-stream: its decode work books as fault cost
        rid = eng.submit([4, 5, 6], max_new_tokens=64)
        next(eng.tokens(rid, timeout=60))
        eng.cancel(rid)
        # deadline already spent at submit → reaped, counted as expiry
        rid2 = eng.submit([7, 8, 9], max_new_tokens=8, timeout_s=0.0)
        assert eng.wait_idle(timeout=30)
        deadline = time.monotonic() + 10
        books = eng.ledger_books()
        while time.monotonic() < deadline and not slo.books_balanced(books):
            time.sleep(0.05)  # finish() → books increment is not atomic
            books = eng.ledger_books()
        assert slo.books_balanced(books), books
        assert books["submitted"] == 3 and books["finished"] == 1
        assert books["cancelled"] == 1 and books["failed"] == 1
        # back-compat: stats()["ttft"] keeps its p50/p99 shape, now
        # derived from the log-bucket tape instead of the deque
        st = eng.stats()
        assert set(st["ttft"]) == {"p50", "p99"}
        snap = eng.slo_snapshot()
        assert snap["deployment"] == "slotest" and snap["books"] == books
        done = [
            e for e in snap["flight"]
            if e["outcome"] == "finished"
            and e.get("deployment") == "slotest"
            and e["request_id"] not in (rid, rid2)
        ]
        assert done, snap["flight"]
        entry = done[0]
        assert entry["tenant_class"] == "interactive"
        for stage in ("queue", "prefill", "decode"):
            assert stage in entry["stages"], entry
        # the deadline expiry is a counted fault class
        rep = slo.build_report([snap])
        dep = rep["deployments"]["slotest"]
        assert dep["deadline_expired"] >= 1
        assert dep["goodput_tokens"] >= 6
        assert dep["fault_tokens"].get("cancelled", 0) >= 1
        assert dep["books_balanced"] is True
        # histograms carry per-class quantiles for the finished stream
        assert dep["by_class"]["interactive"]["ttft_s"]["count"] >= 1
        assert dep["itl_s"]["count"] >= 5  # 6 tokens → ≥5 gaps
    finally:
        eng.stop()


def test_flight_recorder_insert_is_cheap():
    """Perf guard (satellite): the recorder must be safe to run
    always-on — 20k inserts with both caps engaged stay well under a
    second (bounded deque append + fixed-heap replace, no growth)."""
    fr = slo.FlightRecorder(slow_slots=32, flagged_slots=128)
    entry = {"request_id": "x", "e2e_s": 1.0, "stages": {}}
    t0 = time.perf_counter()
    for i in range(20_000):
        fr.add(dict(entry), flagged=(i % 3 == 0), slow_key=float(i % 997))
    dt = time.perf_counter() - t0
    assert dt < 1.0, f"20k flight-recorder inserts took {dt:.2f}s"
    assert len(fr.snapshot()) <= 32 + 128


def test_recorder_threadsafe_under_concurrent_writers():
    fr = slo.FlightRecorder(slow_slots=8, flagged_slots=16)
    errs = []

    def spam(tid):
        try:
            for i in range(2000):
                fr.add({"request_id": f"{tid}-{i}"}, flagged=True, slow_key=float(i))
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=spam, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errs and fr.added == 8000
    assert len(fr.snapshot()) <= 24
