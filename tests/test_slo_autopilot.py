"""SLO autopilot: cluster-free decision-path + harness-determinism tests.

Everything here runs without a cluster (ROADMAP CAUTION): the controller
scale/pool decisions and the ingress shed threshold are pure functions,
the load harness replays through an injected stream_fn, and the
idle-cluster ``serve.slo_report()`` regression exercises the degraded
driver-only path directly."""

import logging

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.serve import loadgen
from ray_tpu.serve.config import AutoscalingConfig
from ray_tpu.serve.controller import autoscale_decision, pool_ratio_decision
from ray_tpu.serve.ingress import (
    ITL_ADJUST_MAX,
    ITL_ADJUST_MIN,
    IngressConfig,
    IngressShedError,
    effective_shed_threshold,
    shed_verdict,
)
from ray_tpu.util.chaos import DataFaultPlan, SeededPlanCache, derive_plan_seed


# ---------------------------------------------------------------------------
# controller scale-out decision (TTFT budget burn + hysteresis)

def _cfg(**kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 8)
    kw.setdefault("target_ongoing_requests", 2.0)
    return AutoscalingConfig(**kw)


def test_autoscale_legacy_queue_path_without_slo_target():
    cfg = _cfg()
    desired, reason = autoscale_decision(
        target=2, cfg=cfg, total_load=8.0, ttft_p99_s=0.0
    )
    assert (desired, reason) == (4, "queue_depth")
    # an SLO target without any gossiped TTFT signal stays on the queue
    # path too: never steer on a signal that hasn't landed
    cfg = _cfg(target_ttft_p99_s=0.5)
    desired, reason = autoscale_decision(
        target=2, cfg=cfg, total_load=2.0, ttft_p99_s=0.0
    )
    assert (desired, reason) == (1, "queue_depth")


def test_autoscale_burn_boundaries_and_hysteresis():
    cfg = _cfg(target_ttft_p99_s=1.0, ttft_burn_high=1.0, ttft_burn_low=0.5)
    # burn exactly AT the high threshold scales out (>=)
    desired, reason = autoscale_decision(
        target=2, cfg=cfg, total_load=0.0, ttft_p99_s=1.0
    )
    assert (desired, reason) == (3, "ttft_burn")
    # a hair below: the dead band holds the target even though the
    # queue signal alone would scale all the way down — chaos blips
    # must not thrash replicas
    desired, reason = autoscale_decision(
        target=2, cfg=cfg, total_load=0.0, ttft_p99_s=0.999
    )
    assert (desired, reason) == (2, "hold")
    # burn exactly AT the low threshold releases one replica (<=),
    # but only when the queue signal agrees we're over-provisioned
    desired, reason = autoscale_decision(
        target=3, cfg=cfg, total_load=0.0, ttft_p99_s=0.5
    )
    assert (desired, reason) == (2, "ttft_relax")
    desired, reason = autoscale_decision(
        target=3, cfg=cfg, total_load=12.0, ttft_p99_s=0.5
    )
    assert (desired, reason) == (3, "hold")  # queue says keep them


def test_autoscale_burn_respects_bounds_and_queue_jump():
    cfg = _cfg(target_ttft_p99_s=0.1, max_replicas=4)
    # at max: burn cannot push past max_replicas
    desired, _ = autoscale_decision(
        target=4, cfg=cfg, total_load=0.0, ttft_p99_s=5.0
    )
    assert desired == 4
    # a burst whose queue-derived count exceeds target+1 jumps straight
    # there — burn scale-out is at LEAST one step, not at most
    desired, reason = autoscale_decision(
        target=1, cfg=cfg, total_load=7.9, ttft_p99_s=5.0
    )
    assert (desired, reason) == (4, "ttft_burn")
    # at min: relax cannot go below min_replicas
    desired, _ = autoscale_decision(
        target=1, cfg=_cfg(target_ttft_p99_s=1.0), total_load=0.0, ttft_p99_s=0.1
    )
    assert desired == 1


# ---------------------------------------------------------------------------
# disagg prefill:decode pool-ratio decision

def test_pool_ratio_tracks_token_mix_and_clamps():
    # equal rates, 4 decode replicas -> 4 prefill replicas
    desired, reason = pool_ratio_decision(
        prefill_target=1, n_decode=4, prefill_tokens_per_s=100.0,
        decode_tokens_per_s=100.0, min_replicas=1, max_replicas=8,
    )
    assert (desired, reason) == (4, "token_mix")
    # prefill-light mix shrinks the pool, clamped to min
    desired, _ = pool_ratio_decision(
        prefill_target=3, n_decode=4, prefill_tokens_per_s=1.0,
        decode_tokens_per_s=1000.0, min_replicas=1, max_replicas=8,
    )
    assert desired == 1
    # prefill-heavy mix grows it, clamped to max
    desired, _ = pool_ratio_decision(
        prefill_target=2, n_decode=4, prefill_tokens_per_s=1000.0,
        decode_tokens_per_s=10.0, min_replicas=1, max_replicas=6,
    )
    assert desired == 6


def test_pool_ratio_never_resizes_blind():
    for pf, dec in ((0.0, 50.0), (50.0, 0.0), (0.0, 0.0)):
        desired, reason = pool_ratio_decision(
            prefill_target=3, n_decode=4, prefill_tokens_per_s=pf,
            decode_tokens_per_s=dec, min_replicas=1, max_replicas=8,
        )
        assert (desired, reason) == (3, "no_signal")


# ---------------------------------------------------------------------------
# ingress ITL-derived shed threshold

def test_effective_shed_threshold_static_without_target_or_signal():
    assert effective_shed_threshold(2048.0, None, 0.7) == 2048.0
    assert effective_shed_threshold(2048.0, 0.5, 0.0) == 2048.0
    assert effective_shed_threshold(0.0, 0.5, 0.7) == 0.0  # disabled stays disabled


def test_effective_shed_threshold_scales_with_measured_itl():
    # at-budget ITL reproduces the static threshold exactly
    assert effective_shed_threshold(1000.0, 0.5, 0.5) == pytest.approx(1000.0)
    # 2x over budget halves admission; half-budget doubles it
    assert effective_shed_threshold(1000.0, 0.5, 1.0) == pytest.approx(500.0)
    assert effective_shed_threshold(1000.0, 0.5, 0.25) == pytest.approx(2000.0)
    # clamped both ways
    assert effective_shed_threshold(1000.0, 0.5, 1000.0) == pytest.approx(
        1000.0 * ITL_ADJUST_MIN
    )
    assert effective_shed_threshold(1000.0, 0.5, 1e-6) == pytest.approx(
        1000.0 * ITL_ADJUST_MAX
    )


def test_shed_verdict_uses_itl_derived_watermark():
    cfg = IngressConfig(
        shed_outstanding_per_replica=100.0,
        shed_queue_fraction=1.0,
        shed_itl_target_s=0.5,
    )
    pressure = {
        "replicas": 1, "reporting": 1, "queue_depth": 0,
        "max_queue_depth": 64, "outstanding_tokens": 80.0,
    }
    # no ITL signal: static 100-token watermark admits 80 outstanding
    assert shed_verdict(dict(pressure), 0, cfg) is None
    # measured ITL 2x over budget halves the watermark to 50: shed
    pressure["itl_p99_s"] = 1.0
    assert shed_verdict(dict(pressure), 0, cfg) == "load"
    # higher classes keep their (k+1)x headroom over the derived base
    assert shed_verdict(dict(pressure), 1, cfg) is None


# ---------------------------------------------------------------------------
# master chaos seed (one logged number replays the composite schedule)

def test_derive_plan_seed_deterministic_distinct_nonzero():
    assert derive_plan_seed(1234, "rpc") == derive_plan_seed(1234, "rpc")
    labels = {derive_plan_seed(1234, lab) for lab in ("rpc", "pull", "replica")}
    assert len(labels) == 3
    for s in labels:
        assert s % 2 == 1  # forced odd: never the "generate" sentinel 0
    assert derive_plan_seed(1235, "rpc") != derive_plan_seed(1234, "rpc")


def test_plan_cache_derives_seed_from_master():
    old = (
        GLOBAL_CONFIG.testing_pull_chaos,
        GLOBAL_CONFIG.testing_pull_chaos_seed,
        GLOBAL_CONFIG.testing_chaos_seed,
    )
    try:
        GLOBAL_CONFIG.testing_pull_chaos = "chunk_drop:0.5"
        GLOBAL_CONFIG.testing_pull_chaos_seed = 0
        GLOBAL_CONFIG.testing_chaos_seed = 424242
        cache = SeededPlanCache(
            DataFaultPlan, "pull", "testing_pull_chaos",
            "testing_pull_chaos_seed", logging.getLogger("test"),
        )
        plan = cache.active()
        assert plan.seed == derive_plan_seed(424242, "pull")
        # same master -> same plan seed -> identical injection schedule
        twin = DataFaultPlan("chunk_drop:0.5", derive_plan_seed(424242, "pull"))
        assert [plan.next_fault() for _ in range(32)] == [
            twin.next_fault() for _ in range(32)
        ]
        # an EXPLICIT per-plan seed still wins over the master
        GLOBAL_CONFIG.testing_pull_chaos_seed = 7
        assert cache.active().seed == 7
    finally:
        (
            GLOBAL_CONFIG.testing_pull_chaos,
            GLOBAL_CONFIG.testing_pull_chaos_seed,
            GLOBAL_CONFIG.testing_chaos_seed,
        ) = old


def test_loadgen_chaos_env_one_line():
    spec = loadgen.LoadSpec(
        seed=9, chaos_master_seed=777,
        replica_chaos="kill_mid_decode:1.0:25:1", rpc_chaos="*:delay:0.1:0.05",
    )
    env = loadgen.chaos_env(spec)
    assert env["RAY_TPU_testing_chaos_seed"] == "777"
    assert env["RAY_TPU_testing_replica_chaos"] == "kill_mid_decode:1.0:25:1"
    assert "RAY_TPU_testing_pull_chaos" not in env
    line = loadgen.repro_line(spec)
    assert "RAY_TPU_testing_chaos_seed=777" in line
    assert "LOADGEN_SEED=9" in line


# ---------------------------------------------------------------------------
# trace harness: bit-replayable schedules + scoring

def test_build_trace_bit_replayable():
    spec = loadgen.LoadSpec(seed=31337, duration_s=4.0, base_rate_rps=12.0)
    a = loadgen.build_trace(spec)
    b = loadgen.build_trace(spec)
    assert len(a) > 10
    assert a == b  # same seed => identical arrivals, tenants, prompts
    c = loadgen.build_trace(loadgen.LoadSpec(seed=31338, duration_s=4.0,
                                             base_rate_rps=12.0))
    assert a != c


def test_build_trace_shapes_and_prefix_populations():
    spec = loadgen.LoadSpec(seed=5, duration_s=6.0, base_rate_rps=15.0)
    trace = loadgen.build_trace(spec)
    ts = [r.t_s for r in trace]
    assert ts == sorted(ts) and ts[-1] < spec.duration_s
    classes = {r.tenant_class for r in trace}
    assert classes <= {"interactive", "standard", "batch"}
    for r in trace:
        assert 1 <= len(r.prompt) <= spec.prompt_max + spec.prefix_len
        assert spec.output_min <= r.max_new_tokens <= spec.output_max
    # shared-prefix populations: reusing requests of one tenant lead
    # with the SAME tokens (the radix-cache exercise)
    by_tenant = {}
    for r in trace:
        by_tenant.setdefault(r.tenant, []).append(r)
    shared = 0
    for recs in by_tenant.values():
        heads = {tuple(r.prompt[: spec.prefix_len]) for r in recs
                 if len(r.prompt) > spec.prefix_len}
        if len(heads) < sum(1 for r in recs if len(r.prompt) > spec.prefix_len):
            shared += 1
    assert shared > 0


def test_run_trace_and_score_with_injected_stream():
    spec = loadgen.LoadSpec(seed=2, duration_s=1.0, base_rate_rps=20.0)
    trace = loadgen.build_trace(spec)
    assert len(trace) >= 5

    def stream_fn(req):
        if req.index == 1:
            raise RuntimeError("boom")
        if req.index == 2:
            raise IngressShedError("load", 0.25)
        return iter([1, 2, 3])

    run = loadgen.run_trace(
        trace, spec=spec, stream_fn=stream_fn, time_scale=0.01, max_workers=8
    )
    assert len(run.records) == len(trace)
    outcomes = {r["request_id"]: r["outcome"] for r in run.records}
    assert outcomes[trace[1].request_id] == "error"
    assert outcomes[trace[2].request_id] == "shed"
    assert all(
        r["n_tokens"] == 3 for r in run.records if r["outcome"] == "ok"
    )
    report = {
        "flight_recorder": [
            {"request_id": trace[1].request_id,
             "slowest_stage": "router.dispatch", "flags": ["fault"]}
        ],
        "deployments": {"llm": {"goodput_fraction": 0.9}},
    }
    s = loadgen.score(
        run, ttft_slo_s=10.0, itl_slo_s=1.0, report=report,
        status={"llm": {"last_scale": {}}},
    )
    ok = s["ok"]
    # the one error counts as a miss; sheds are excluded from the base
    assert s["ttft_attainment"] == pytest.approx(ok / (ok + 1))
    assert s["itl_attainment"] == 1.0
    assert s["goodput_fraction"]["llm"] == 0.9
    assert s["autoscaler_lag_s"] is None
    attr = s["miss_attribution"]
    assert attr[trace[1].request_id]["stage"] == "router.dispatch"
    assert "LOADGEN_SEED=2" in s["repro"]


def test_score_autoscaler_lag_from_last_scale_stamp():
    run = loadgen.HarnessRun(
        spec=loadgen.LoadSpec(seed=1),
        records=[{"request_id": "r0", "tenant": "t", "tenant_class": "standard",
                  "outcome": "ok", "ttft_s": 0.01, "e2e_s": 0.02,
                  "n_tokens": 2, "itl_max_s": 0.01, "t_s": 0.0}],
        itl_gaps=[0.01],
        started_wall=1000.0,
        duration_s=2.0,
    )
    status = {
        "llm": {"last_scale": {"ts": 1001.5, "from": 1, "to": 2,
                               "reason": "ttft_burn"}},
        "ing": {"last_scale": {}},
    }
    s = loadgen.score(run, ttft_slo_s=1.0, status=status)
    assert s["autoscaler_lag_s"] == pytest.approx(1.5)
    # a scale-DOWN (or a pre-run scale) is not lag
    status["llm"]["last_scale"] = {"ts": 1001.5, "from": 2, "to": 1}
    assert loadgen.score(run, ttft_slo_s=1.0, status=status)[
        "autoscaler_lag_s"
    ] is None


# ---------------------------------------------------------------------------
# satellite fix: slo_report off-cluster / idle must degrade, not error

def test_slo_report_without_cluster_is_wellformed_and_fast():
    import time as _time

    assert not ray_tpu.is_initialized()
    t0 = _time.monotonic()
    rep = serve.slo_report(timeout=5.0)
    assert _time.monotonic() - t0 < 5.0  # degraded, under the deadline
    assert set(rep) >= {"deployments", "counters", "flight_recorder", "buckets"}
    # driver-only degraded report: well-formed dict/list shapes (the
    # driver ledger is process-global, so contents may be non-empty when
    # earlier tests in this pytest process exercised the serving path)
    assert isinstance(rep["deployments"], dict)
    assert isinstance(rep["flight_recorder"], list)


# ---------------------------------------------------------------------------
# ingress door: client-observed TTFB gossip (the burn signal's eyes on
# router-side waits the engines' own TTFT clocks never contain)

def test_ingress_door_gossips_windowed_ttfb_p99():
    from ray_tpu.serve.ingress import HttpIngress

    class _Handle:
        _router = None

    import time as _time

    door = HttpIngress(IngressConfig(target="llm"), handle=_Handle())
    try:
        rs = door.routing_stats()
        # no samples yet: 0.0 means "no signal", and the controller's
        # burn path treats it as such (never steer blind)
        assert rs["target"] == "llm" and rs["ttfb_p99_s"] == 0.0
        for i, ttfb in enumerate((0.01, 0.02, 0.03, 1.5)):
            # _flight_ttfb records the sample only for requests it saw
            # forwarded (the in-flight entry is the once-only gate)
            door._inflight_t0[f"r{i}"] = _time.monotonic()
            door._flight_ttfb(f"r{i}", "standard", ttfb, "ok")
        rs = door.routing_stats()
        # p99 over a handful of samples is the max — the tail the burn
        # signal must see
        assert rs["ttfb_p99_s"] == pytest.approx(1.5)
        assert rs["ingress"] is True
        assert not door._inflight_t0  # every gate consumed exactly once
        # a request STALLED waiting for its first byte contributes its
        # current age live — the burn signal sees a dead-replica stall
        # while it is happening, not after
        door._inflight_t0["stuck"] = _time.monotonic() - 20.0
        assert door._ttfb_p99() >= 20.0
        door._inflight_t0.clear()
        # duplicate terminal report for an already-sampled request is
        # dropped, not double-counted
        n_before = len(door._recent_ttfb)
        door._flight_ttfb("r0", "standard", 9.9, "ok")
        assert len(door._recent_ttfb) == n_before
    finally:
        door.stop()


class _NullProvider:
    """Provider double: empty fleet, records launch/terminate calls."""

    def __init__(self):
        self.created = []
        self.terminated = []

    def non_terminated_nodes(self):
        return []

    def create_node(self, node_type):
        self.created.append(node_type.name)

    def terminate_node(self, node_id):
        self.terminated.append(node_id)


def _node_autoscaler(demand, **cfg_kwargs):
    from ray_tpu.autoscaler import (
        AutoscalerConfig,
        NodeTypeConfig,
        StandardAutoscaler,
    )

    provider = _NullProvider()

    class _Scaler(StandardAutoscaler):
        def _demand(self):
            return demand

    cfg = AutoscalerConfig(
        node_types=[NodeTypeConfig("worker", {"CPU": 4}, max_workers=4)],
        **cfg_kwargs,
    )
    return _Scaler(provider, cfg), provider


def test_node_autoscaler_stats_summarize_pass():
    empty = {
        "pending_tasks": [],
        "pending_actors": [],
        "pending_bundles": [],
        "nodes": [],
    }
    scaler, provider = _node_autoscaler(empty)
    assert scaler.stats() == {}  # nothing before the first pass
    scaler.update()
    st = scaler.stats()
    assert st["demand_shapes"] == 0 and st["unmet_shapes"] == 0
    assert st["launches"] == {} and st["terminated_slices"] == 0
    assert st["pass_duration_s"] >= 0.0 and st["ts"] > 0

    busy = dict(empty, pending_actors=[{"CPU": 4}])
    scaler2, provider2 = _node_autoscaler(busy)
    scaler2.update()
    st2 = scaler2.stats()
    assert st2["demand_shapes"] == 1 and st2["unmet_shapes"] == 1
    assert st2["launches"] == {"worker": 1}
    assert provider2.created == ["worker"]


def test_node_autoscaler_kick_skips_the_interval_wait():
    import time as _time

    empty = {
        "pending_tasks": [],
        "pending_actors": [],
        "pending_bundles": [],
        "nodes": [],
    }
    # interval so long that only kick() can trigger a pass in-test
    scaler, _provider = _node_autoscaler(empty, update_interval_s=60.0)
    scaler.start()
    try:
        assert scaler.stats() == {}
        scaler.kick()
        deadline = _time.monotonic() + 5.0
        while _time.monotonic() < deadline and not scaler.stats():
            _time.sleep(0.02)
        assert scaler.stats(), "kick() did not trigger a reconcile pass"
    finally:
        t0 = _time.monotonic()
        scaler.stop()  # must unblock the 60s wait immediately
        assert _time.monotonic() - t0 < 5.0
