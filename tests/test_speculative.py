"""Speculative decoding (PR 19): propose / one-step verify / byte-exact
accept-rollback on the paged KV cache.

Everything here is cluster-free and lean per the ROADMAP caution: tiny
model, ``warmup=False`` everywhere except the single recompile-gate test
(which needs a real warmup to assert zero post-warmup compiles across
the target runner, the verify step AND the draft runner).

The load-bearing invariants:

* output streams are BYTE-IDENTICAL to a plain engine for temp=0 and
  seeded temp>0, at several k including a k whose verify window
  straddles a block boundary (rollback then exercises block rewind);
* rejected/unverified positions never reach the radix prefix index and
  never publish to the KV tier — adverts cap at the verified cursor;
* block-manager books balance exactly after rollback-heavy runs;
* adaptive k shrinks under low acceptance and recovers, without ever
  recompiling (the verify bucket stays sized for speculative_k+1).
"""

import pytest

pytest.importorskip("jax")

import jax  # noqa: E402

from ray_tpu.inference.engine import EngineConfig, InferenceEngine  # noqa: E402
from ray_tpu.inference.kv_cache import (  # noqa: E402
    PagedBlockManager,
    _chain_digest,
)
from ray_tpu.inference.speculative import NgramProposer  # noqa: E402
from ray_tpu.models.llama import LlamaConfig, init_params  # noqa: E402

#: repetitive prompt: the ngram proposer finds matches, so speculative
#: steps exercise BOTH accept and rollback against the random target
PROMPT = [1, 2, 3, 4, 5, 6, 7, 1, 2, 3, 4, 5]


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.tiny()


@pytest.fixture(scope="module")
def params(cfg):
    return init_params(cfg, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def draft_params(cfg):
    # different init -> the draft disagrees with the target often enough
    # to exercise rollback, agrees rarely enough to exercise accept
    return init_params(cfg, jax.random.PRNGKey(7))


def _ec(**overrides):
    kw = dict(
        num_blocks=64, block_size=8, prefill_buckets=(8, 16),
        decode_buckets=(1, 2, 4), max_decode_batch=4,
        max_new_tokens_default=8, warmup=False,
    )
    kw.update(overrides)
    return EngineConfig(**kw)


def _run(cfg, params, ec, *, temp=0.0, seed=None, n=20, **gen_kw):
    eng = InferenceEngine(cfg, params, ec).start()
    try:
        out = list(
            eng.generate(
                PROMPT, max_new_tokens=n, temperature=temp, seed=seed,
                **gen_kw,
            )
        )
        return out, eng.stats()
    finally:
        eng.stop()


def _digests(tokens, bs=8):
    """Full-block chain digests of ``tokens`` (tier + prefix key space)."""
    out, prev = [], b""
    for end in range(bs, len(tokens) + 1, bs):
        prev = _chain_digest(prev, tokens[end - bs : end])
        out.append(prev)
    return out


# ---------------------------------------------------------------------------
# units: proposer + rollback bookkeeping (no engine, no jit)


def test_ngram_proposer_prompt_lookup():
    p = NgramProposer(max_ngram=3, min_ngram=1)
    # trailing [1,2,3] recurs at the start; the continuation follows it
    assert p.propose([1, 2, 3, 9, 8, 1, 2, 3], 3) == [9, 8, 1]
    # k truncates at the end of the context
    assert p.propose([1, 2, 3, 9, 8, 1, 2, 3], 99) == [9, 8, 1, 2, 3]
    # longest n-gram wins over a shorter, more recent match
    assert p.propose([5, 6, 7, 4, 7, 5, 6, 7], 1) == [4]
    # most recent PRIOR occurrence wins within one n-gram length
    assert p.propose([2, 8, 2, 9, 2], 1, request_id="r") == [9]
    # nothing repeats -> no draft (engine degrades to plain decode)
    assert p.propose([1, 2, 3, 4, 5], 4) == []
    assert p.propose([1, 2, 3], 0) == []
    with pytest.raises(ValueError):
        NgramProposer(max_ngram=1, min_ngram=2)


def test_trim_to_rewinds_block_books_exactly():
    bm = PagedBlockManager(16, 8)
    base = bm.stats()["free_blocks"]
    assert bm.grow_to("r", 12)  # 2 blocks for the committed context
    assert bm.grow_to("r", 12 + 7)  # +1 block for a k=7 verify window
    allocs = bm.total_allocs
    assert bm.stats()["free_blocks"] == base - 3
    # full rollback of the speculative tail: back to 12 tokens
    assert bm.trim_to("r", 12) == 1
    assert bm.stats()["free_blocks"] == base - 2
    # idempotent / no-op when already at (or below) the cursor
    assert bm.trim_to("r", 12) == 0
    assert bm.trim_to("missing", 4) == 0
    bm.free("r")
    assert bm.stats()["free_blocks"] == base
    assert bm.total_allocs == allocs and bm.total_frees == allocs


# ---------------------------------------------------------------------------
# byte-exactness: speculative output == plain output, always


@pytest.mark.parametrize("temp,seed", [(0.0, None), (0.8, 123)])
def test_cross_engine_byte_exact_ngram(cfg, params, temp, seed):
    ref, _ = _run(cfg, params, _ec(), temp=temp, seed=seed)
    # k=7 -> an 8-wide verify window on block_size 8: windows straddle
    # block boundaries, so rollback exercises tail-block rewind
    for k in (2, 7):
        out, st = _run(
            cfg, params, _ec(speculative_k=k), temp=temp, seed=seed
        )
        assert out == ref, (k, temp)
        assert st["speculative"]["proposed_tokens"] > 0


def test_cross_engine_byte_exact_draft_model(cfg, params, draft_params):
    # a DISAGREEING draft model: heavy rollback traffic, same bytes.
    # temp>0 makes the target sample while the draft argmaxes — the
    # worst case for acceptance, the best case for rollback coverage.
    for temp, seed in ((0.0, None), (0.8, 123)):
        ref, _ = _run(cfg, params, _ec(), temp=temp, seed=seed)
        out, st = _run(
            cfg,
            params,
            _ec(
                speculative_k=3,
                speculative_draft="model",
                draft_config=cfg,
                draft_params=draft_params,
                draft_num_blocks=32,
            ),
            temp=temp,
            seed=seed,
        )
        assert out == ref, temp
        sp = st["speculative"]
        assert sp["draft"] == "model" and sp["proposed_tokens"] > 0


def test_draft_equals_target_accepts_everything(cfg, params):
    # draft == target -> greedy drafts always match the greedy sample
    out, st = _run(
        cfg,
        params,
        _ec(
            speculative_k=4,
            speculative_draft="model",
            draft_config=cfg,
            draft_params=params,
            speculative_adaptive=False,
        ),
    )
    ref, _ = _run(cfg, params, _ec())
    sp = st["speculative"]
    assert out == ref
    assert sp["rollbacks"] == 0
    assert sp["accepted_tokens"] == sp["proposed_tokens"] > 0


def test_per_request_off_switch(cfg, params):
    ref, _ = _run(cfg, params, _ec())
    out, st = _run(
        cfg, params, _ec(speculative_k=4), speculative=False
    )
    assert out == ref
    assert st["speculative"]["proposed_tokens"] == 0


# ---------------------------------------------------------------------------
# isolation: rejected positions never escape the verified cursor


def test_rollback_never_pollutes_prefix_index_or_tier(cfg, params):
    from ray_tpu.inference import kv_transfer

    ec = _ec(
        speculative_k=4,
        kv_transfer_enabled=True,
        kv_tier_enabled=True,
        speculative_adaptive=False,
    )
    eng = InferenceEngine(cfg, params, ec).start()
    try:
        # an always-wrong proposer: every verify step writes a rejected
        # tail into the paged cache, every step rolls back
        class _Garbage:
            def propose(self, ctx, k, request_id=""):
                return [255] * k

            def release(self, request_id):
                pass

            def compile_count(self):
                return 0

            def recompiles_after_warmup(self):
                return 0

        eng.spec = _Garbage()
        out = list(eng.generate(PROMPT, max_new_tokens=20, temperature=0.0))
        st = eng.stats()
        assert st["speculative"]["rollbacks"] > 0
        assert eng.flush_tier_writebacks()
        # every tier advert AND every indexed prefix digest must key
        # verified tokens only — the chain digests of prompt+generated
        # (rejected drafts were emitted by neither)
        verified = set(d.hex() for d in _digests(PROMPT + out))
        assert set(eng._tier_adverts) <= verified
        assert st["speculative"]["proposed_tokens"] > 0
        with eng.blocks._lock:
            indexed = set(d.hex() for d in eng.blocks._index)
        assert indexed <= verified
        # block books balance exactly after a rollback-heavy run: no
        # holders, nothing pinned — the only surviving blocks are the
        # verified full blocks parked in the prefix LRU
        bs = eng.blocks.stats()
        assert bs["holders"] == 0
        assert bs["used_blocks"] == 0
        n_full_verified = (len(PROMPT) + len(out) - 1) // bs["block_size"]
        assert bs["prefix_cached_blocks"] == n_full_verified
    finally:
        eng.stop()
    with kv_transfer._LOCAL_TIER_LOCK:
        kv_transfer._LOCAL_TIER.clear()


# ---------------------------------------------------------------------------
# adaptive k + compile gate


def test_adaptive_k_shrinks_and_recovers(cfg, params):
    eng = InferenceEngine(cfg, params, _ec(speculative_k=4)).start()
    try:
        assert eng.scheduler.spec_k_live == 4
        # low-acceptance window -> controller sheds one draft token
        eng._spec_proposed, eng._spec_accepted = 16, 1
        eng._next_gauge_refresh = 0.0
        eng._update_gauges(0)
        assert eng.scheduler.spec_k_live == 3
        assert eng.stats()["speculative"]["k_live"] == 3
        # hot window -> grows back toward the configured ceiling
        eng._spec_proposed, eng._spec_accepted = 32, 17
        eng._next_gauge_refresh = 0.0
        eng._update_gauges(0)
        assert eng.scheduler.spec_k_live == 4
        # tiny windows (< 8 proposals) never steer
        eng._spec_proposed, eng._spec_accepted = 33, 17
        eng._next_gauge_refresh = 0.0
        eng._update_gauges(0)
        assert eng.scheduler.spec_k_live == 4
    finally:
        eng.stop()


def test_zero_recompiles_after_warmup_with_draft(cfg, params):
    # the ONE warmed engine in this module: minimal buckets, and the
    # warmup set must cover target prefill+decode, the verify bucket
    # (speculative_k+1) AND the draft runner's own buckets
    ec = _ec(
        prefill_buckets=(16,),
        decode_buckets=(1,),
        max_decode_batch=1,
        warmup=True,
        speculative_k=2,
        speculative_draft="model",
        draft_config=cfg,
        draft_params=params,
        draft_num_blocks=32,
        draft_prefill_buckets=(16,),
        speculative_adaptive=False,
    )
    eng = InferenceEngine(cfg, params, ec).start()
    try:
        warm = eng.stats()["compile_count"]
        out = list(eng.generate(PROMPT, max_new_tokens=10, temperature=0.0))
        st = eng.stats()
        assert len(out) == 10
        assert st["speculative"]["accepted_tokens"] > 0
        assert st["compile_count"] == warm
        assert st["recompiles_after_warmup"] == 0
    finally:
        eng.stop()


def test_plain_engine_keeps_exact_compile_count(cfg, params):
    # the verify jit is constructed unconditionally but never traced on
    # a plain engine — compile books must not move (test_inference pins
    # the same invariant with its own bucket set; this pins it next to
    # the code that could break it)
    ec = _ec(
        prefill_buckets=(8, 16), decode_buckets=(1, 2),
        max_decode_batch=2, warmup=True,
    )
    eng = InferenceEngine(cfg, params, _ec()).start()
    eng.stop()
    eng = InferenceEngine(cfg, params, ec).start()
    try:
        assert eng.runner.compile_count() == 2 + 2 + 1
        assert eng.stats()["compile_count"] == 2 + 2 + 1
    finally:
        eng.stop()
