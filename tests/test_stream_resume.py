"""ISSUE 10: resumable LLM streams — exactly-once token delivery across
replica death.

Three layers under test:

* deterministic continuation — engine sampling keyed on
  ``(request seed, absolute position)``: a request resubmitted as
  ``prompt + generated[:k]`` provably samples token k+1 identically,
  on ANY fresh engine with the same params;
* seq-numbered streaming + router resume — mid-stream replica death is
  re-dispatched to a survivor with the delivered tokens replayed as
  prompt and ``resume_from=seq``; the ``SeqGate`` suppresses boundary
  duplicates so the client sequence has no gaps and no repeats;
* seeded replica-kill chaos + health restart — ``ReplicaFaultPlan``
  (``kill_mid_decode`` / ``kill_mid_prefill`` / ``stall``) drives the
  E2E gate: the affinity-hot replica SIGKILLed mid-decode under 8
  concurrent streams, every client receiving the byte-exact token
  sequence of an undisturbed run; a stalled (not dead) engine is caught
  by the serve controller's ``replica.health()`` poll and restarted.
"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve

pytest.importorskip("jax")

import jax  # noqa: E402

from ray_tpu.core.streaming import SeqGate  # noqa: E402
from ray_tpu.inference.engine import EngineConfig, InferenceEngine  # noqa: E402
from ray_tpu.models.llama import LlamaConfig, init_params  # noqa: E402
from ray_tpu.util.chaos import ReplicaFaultPlan  # noqa: E402


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.tiny()


@pytest.fixture(scope="module")
def params(cfg):
    return init_params(cfg, jax.random.PRNGKey(0))


#: env-armed kill plan for the module's ONE shared cluster: every
#: runtime process (daemons -> workers, incl. controller-spawned
#: replacements) inherits it; the driver's GLOBAL_CONFIG stays clean
#: (env is only read at import), so driver-local reference engines
#: never consult it
CHAOS_SPEC, CHAOS_SEED = "kill_mid_decode:1.0:6", 20260804


@pytest.fixture(scope="module")
def chaos_cluster():
    """One cluster for both E2E chaos tests — cluster boot/teardown was
    the dominant suite cost of this module. The kill plan must be in
    the env BEFORE init (daemons capture it for every worker they
    spawn), which makes it module-wide: each test must stay inside the
    per-process kill window it implies (see the stall test's note)."""
    import os

    os.environ["RAY_TPU_testing_replica_chaos"] = CHAOS_SPEC
    os.environ["RAY_TPU_testing_replica_chaos_seed"] = str(CHAOS_SEED)
    ray_tpu.init(num_cpus=4)
    try:
        yield
    finally:
        # the plan must not outlive this module: a later module's
        # cluster would inherit it and keep dying
        os.environ.pop("RAY_TPU_testing_replica_chaos", None)
        os.environ.pop("RAY_TPU_testing_replica_chaos_seed", None)
        from ray_tpu.core.config import GLOBAL_CONFIG

        GLOBAL_CONFIG.testing_replica_chaos = ""
        GLOBAL_CONFIG.testing_replica_chaos_seed = 0
        serve.shutdown()
        ray_tpu.shutdown()


def _engine(cfg, params, **overrides):
    kw = dict(
        num_blocks=64, block_size=8, prefill_buckets=(8, 32),
        decode_buckets=(1, 4), max_decode_batch=4, max_new_tokens_default=8,
    )
    kw.update(overrides)
    return InferenceEngine(cfg, params, EngineConfig(**kw)).start()


# ---------------------------------------------------------------------------
# units: SeqGate + ReplicaFaultPlan


def test_seq_gate_admits_once_suppresses_duplicates_and_fails_gaps():
    g = SeqGate()
    assert [g.admit(i) for i in (0, 1, 2)] == [True, True, True]
    # THE boundary case: the replica died after emitting token k but
    # before the router delivered it — the resumed producer re-emits k
    # and the gate delivers it exactly once; any seq at or below the
    # delivered horizon afterwards is a replayed duplicate, suppressed
    assert g.admit(2) is False
    assert g.admit(0) is False
    assert g.admit(3) is True
    with pytest.raises(RuntimeError):
        g.admit(5)  # a gap must fail loudly, never skip silently
    g2 = SeqGate(start=4)
    assert g2.admit(3) is False and g2.admit(4) is True


def test_replica_fault_plan_deterministic_bounded_and_validated():
    spec = "kill_mid_decode:0.5,stall:0.3:2.0:2"
    phases = ["prefill", "decode", "decode", "prefill"] * 10
    a = ReplicaFaultPlan(spec, 1234)
    b = ReplicaFaultPlan(spec, 1234)
    # the full injection schedule is a pure function of (seed, the
    # ordered consult sequence) — reproducible from the logged seed alone
    assert [a.consult(p) for p in phases] == [b.consult(p) for p in phases]
    assert a.consults == len(phases)
    # caps honored: at most 1 kill + 2 stalls injected per process
    assert a.injections <= 3
    # skip window: prob 1 + skip 3 fires deterministically on the 4th
    # matching-phase consult, exactly once (default cap 1)
    d = ReplicaFaultPlan("kill_mid_decode:1.0:3", 7)
    out = [d.consult("decode") for _ in range(6)]
    assert out == [None, None, None, ("kill_mid_decode", 3.0), None, None]
    # prefill consults never tick a decode rule's phase window
    e = ReplicaFaultPlan("kill_mid_decode:1.0:1", 7)
    assert [e.consult("prefill") for _ in range(5)] == [None] * 5
    assert e.consult("decode") is None and e.consult("decode") is not None
    with pytest.raises(ValueError):
        ReplicaFaultPlan("reply_drop:1.0", 1)  # rpc mode, not a replica mode
    with pytest.raises(ValueError):
        ReplicaFaultPlan("kill_mid_decode", 1)


# ---------------------------------------------------------------------------
# deterministic continuation (engine level)


def test_cross_engine_determinism_and_midstream_resume(cfg, params):
    prompt = [3, 7, 11, 5]
    e1 = _engine(cfg, params)
    e2 = _engine(cfg, params)
    try:
        a = list(e1.generate(prompt, max_new_tokens=12, temperature=0.8, seed=42))
        assert len(a) == 12
        # two FRESH engines, same seed + prompt -> identical tokens
        b = list(e2.generate(prompt, max_new_tokens=12, temperature=0.8, seed=42))
        assert b == a
        # mid-stream resubmit-with-prefix continues identically: token
        # k+1 samples at the same absolute position whether its prefix
        # arrived as prompt (resume re-prefill) or as decode output
        for k in (1, 5, 11):
            tail = list(
                e2.generate(
                    prompt + a[:k], max_new_tokens=12 - k,
                    temperature=0.8, seed=42,
                )
            )
            assert tail == a[k:], f"divergence resuming at k={k}"
        # greedy streams resume exactly too (argmax needs no seed)
        g = list(e1.generate(prompt, max_new_tokens=12))
        assert list(e2.generate(prompt + g[:4], max_new_tokens=8)) == g[4:]
    finally:
        e1.stop()
        e2.stop()


def test_spec_midstream_resume_byte_exact_on_any_survivor(cfg, params):
    """PR 19 regression: a stream generated WITH speculation that dies
    mid-stream resumes byte-exact on a survivor whether the survivor
    speculates or not — acceptance is exact-match against the same
    (seed, absolute-position) sampler, so the delivered prefix replayed
    as prompt continues identically in all four (dead, survivor)
    speculation combinations."""
    prompt = [3, 7, 11, 5, 3, 7, 11, 5]  # repetitive: drafts actually fire
    e_spec = _engine(cfg, params, speculative_k=3, warmup=False)
    e_plain = _engine(cfg, params, warmup=False)
    try:
        ref = list(
            e_plain.generate(prompt, max_new_tokens=12, temperature=0.7, seed=42)
        )
        a = list(
            e_spec.generate(prompt, max_new_tokens=12, temperature=0.7, seed=42)
        )
        assert a == ref, "speculative stream diverged from plain"
        # the replica died after delivering a[:k]; the router replays the
        # prefix as prompt on a survivor with speculation on OR off
        for k in (1, 5, 11):
            for survivor in (e_plain, e_spec):
                tail = list(
                    survivor.generate(
                        prompt + a[:k], max_new_tokens=12 - k,
                        temperature=0.7, seed=42,
                    )
                )
                assert tail == a[k:], (k, survivor is e_spec)
        # and the mirror: a plain-engine stream resumed on a SPECULATIVE
        # survivor (greedy this time) — same bytes
        g = list(e_plain.generate(prompt, max_new_tokens=12))
        assert list(e_spec.generate(prompt + g[:4], max_new_tokens=8)) == g[4:]
        assert e_spec.stats()["speculative"]["proposed_tokens"] > 0
    finally:
        e_spec.stop()
        e_plain.stop()


def test_resumed_request_keeps_seq_under_preemption(cfg, params):
    """Resume-under-preemption: a RESUMED request (prompt = original +
    delivered prefix) that is evicted for blocks and readmitted still
    continues the exact sequence — eviction snapshots prompt+generated,
    readmission re-prefills, and sampling stays keyed on absolute
    position throughout."""
    ref = _engine(cfg, params)
    prompt = [5, 9, 2, 4, 1, 6, 3] * 2  # 14 tokens
    try:
        full = list(ref.generate(prompt, max_new_tokens=40, temperature=0.6, seed=9))
    finally:
        ref.stop()
    # pool too small for two grown sequences (same sizing as the
    # engine preemption test): the low-priority RESUMED request gets
    # evicted mid-decode by the high-priority competitor
    eng = _engine(
        cfg, params, num_blocks=11, prefill_buckets=(16, 32),
        decode_buckets=(1, 2), max_decode_batch=2, max_new_tokens_default=40,
    )
    try:
        k = 7  # resume point: 7 tokens were already delivered elsewhere
        lo = eng.submit(
            prompt + full[:k], max_new_tokens=40 - k,
            temperature=0.6, seed=9, priority=0,
        )
        hi = eng.submit([8, 9, 10, 11, 12, 13] * 2, max_new_tokens=40, priority=1)
        out_lo = list(eng.tokens(lo, timeout=60))
        list(eng.tokens(hi, timeout=60))
        assert eng.scheduler.total_preempted > 0, "preemption never happened"
        assert out_lo == full[k:], "resumed request diverged across preemption"
        assert eng.blocks.used_blocks == 0
    finally:
        eng.stop()


def test_resume_after_delivered_eos_emits_nothing(cfg, params):
    """The replica died after emitting EOS but before the end-of-stream
    signal reached the router: the resumed request's prompt ENDS with
    the delivered EOS. The engine's EOS check applies only to sampled
    tokens, so without the guard the resume would decode past it and
    stream tokens an undisturbed run never produced."""
    from ray_tpu.inference.serve_llm import LLMServer

    server = LLMServer(
        cfg,
        EngineConfig(
            num_blocks=64, block_size=8, prefill_buckets=(8, 32),
            decode_buckets=(1, 4), max_decode_batch=4,
        ),
        params=params, export_metrics=False,
    )
    try:
        out = list(server.generate({
            "prompt": [3, 1, 4, 99], "max_new_tokens": 8,
            "eos_token": 99, "resume_from": 3,
        }))
        assert out == [], "resume decoded past a delivered EOS"
        # same resume WITHOUT eos keeps generating, seq-numbered from 3
        # the replica yields TokenChunk bursts of (seq, tok) pairs —
        # flatten (the serve router does the same before clients see it)
        out2 = [p for chunk in server.generate({
            "prompt": [3, 1, 4, 99], "max_new_tokens": 8,
            "resume_from": 3, "request_id": "no-eos",
        }) for p in chunk]
        assert len(out2) == 5 and out2[0][0] == 3 and out2[-1][0] == 7
        # an eos INSIDE the original prompt (resume_from=0: nothing was
        # delivered yet) must not close the stream
        out3 = list(server.generate({
            "prompt": [3, 99, 4], "max_new_tokens": 4,
            "eos_token": 99, "resume_from": 0, "request_id": "eos-in-prompt",
        }))
        assert len(out3) >= 1
        # room-clamped cap boundary: original prompt 60 tokens at
        # max_seq_len 64 clamps max_new_tokens 10 -> 4; all 4 delivered,
        # replica dies before end-of-stream. The resume (prompt now 64
        # tokens, resume_from=4) must CLOSE the stream — naive
        # max_new - resume_from math says 6 remaining and the engine
        # would reject the full-context prompt as an app error
        L = cfg.max_seq_len
        out4 = list(server.generate({
            "prompt": list(range(1, L - 3)) + [7, 7, 7, 7],
            "max_new_tokens": 10, "resume_from": 4,
            "request_id": "room-clamped",
        }))
        assert out4 == [], "resume past a room-clamped cap must close"
    finally:
        server.engine.stop()


# ---------------------------------------------------------------------------
# serve E2E: the chaos gate


@pytest.mark.chaos
def test_e2e_hot_replica_killed_mid_decode_byte_exact(
    cfg, params, chaos_cluster
):
    """ISSUE 10 acceptance gate: a seeded ReplicaFaultPlan SIGKILLs the
    affinity-hot replica mid-decode under 8 concurrent streams; every
    client receives the byte-exact token sequence of an undisturbed run
    (no gaps, no duplicates, zero errors), the resume/restart counters
    prove the deaths actually happened, and the plan's schedule
    reproduces from the logged seed alone."""
    from ray_tpu.core.config import GLOBAL_CONFIG
    from ray_tpu.observability.rpc_metrics import STREAM_RESUMES

    from ray_tpu.observability import slo as _slo
    from ray_tpu.observability.rpc_metrics import STREAM_RESUME_REPLAY_TOKENS

    SPEC, SEED = CHAOS_SPEC, CHAOS_SEED
    # speculative_k=2 (PR 19): the kill now lands mid-SPECULATIVE-decode
    # — rollback state, partially-accepted windows and all — and the
    # resumed streams must still be byte-exact. The reference engine
    # shares the config, but exact-match acceptance makes its output
    # identical to a plain engine's anyway; chaos consults tick once per
    # step whether the slot speculated or not, so the seeded kill
    # schedule is unchanged.
    ec = EngineConfig(
        num_blocks=64, block_size=8, prefill_buckets=(8, 32),
        decode_buckets=(1, 8), max_decode_batch=8, max_new_tokens_default=8,
        speculative_k=2,
    )
    shared = [11, 3, 7, 5, 2, 9, 8, 6] * 3  # 24 tokens = 3 full blocks
    n, max_new = 8, 12
    prompts = {i: shared + [60 + i] for i in range(n)}
    # expected sequences from an undisturbed LOCAL engine with the same
    # params seed — byte-exactness across processes is exactly what
    # deterministic continuation guarantees. Safe to compute with the
    # module's kill plan armed in the ENV: the driver's GLOBAL_CONFIG
    # read the env at import (long before the fixture exported the
    # plan), so driver-local engines never consult it.
    ref = InferenceEngine(cfg, params, ec).start()
    try:
        expected = {
            i: list(ref.generate(
                prompts[i], max_new_tokens=max_new,
                temperature=0.7, seed=100 + i,
            ))
            for i in range(n)
        }
    finally:
        ref.stop()
    # the module cluster armed the env-driven plan before init (worker
    # processes inherit: driver env -> daemon env -> worker env;
    # system_config reaches only daemons): EVERY replica — including
    # controller-spawned replacements — consults the same seeded
    # schedule, so deaths keep happening until streams outrun the
    # per-process kill: the multi-death convergence the resume protocol
    # must survive.
    old_weight = GLOBAL_CONFIG.serve_affinity_weight
    GLOBAL_CONFIG.serve_affinity_weight = 1e6  # pin streams to the warm replica
    try:
        dep = serve.llm_deployment(
            cfg, engine=ec, name="llmx", num_replicas=2,
            route_prefix="/llmx", ray_actor_options={"num_cpus": 0.25},
        )
        handle = serve.run(dep.bind())
        ctrl = ray_tpu.get_actor("__serve_controller__")
        ray_tpu.get(
            ctrl.wait_status.remote("llmx", min_replicas=2, timeout_s=90),
            timeout=120,
        )
        # warm ONE replica (2 decode consults tick its kill window) and
        # let its gossip reach the router so affinity pins what follows
        list(handle.stream(
            {"prompt": shared + [42], "max_new_tokens": 2},
            _method="generate", _timeout=120,
        ))
        time.sleep(3 * GLOBAL_CONFIG.serve_replica_stats_period_s)

        resumes_before = STREAM_RESUMES._values.get(("llmx",), 0.0)
        replay_before = STREAM_RESUME_REPLAY_TOKENS._values.get((), 0.0)
        # ISSUE 15 ledger setup: sampled traces give the router ledger a
        # resolvable trace id (restored in finally — the observability
        # module asserts the default stays 0), and the driver-recorder
        # high-water mark isolates THIS test's entries for the exact
        # replay-token reconcile
        GLOBAL_CONFIG.trace_sample_rate = 1.0
        led_before_ids = {
            e.get("request_id") for e in _slo.flight_recorder().snapshot()
        }
        results, errors = {}, {}

        def consume(i):
            try:
                results[i] = list(handle.stream(
                    {"prompt": prompts[i], "max_new_tokens": max_new,
                     "temperature": 0.7, "seed": 100 + i,
                     "request_id": f"slo{i}"},
                    _method="generate", _timeout=180,
                ))
            except Exception as e:  # noqa: BLE001
                errors[i] = e

        threads = [threading.Thread(target=consume, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=240)
        assert not errors, errors
        assert results == expected, {
            i: (results.get(i), expected[i]) for i in range(n)
            if results.get(i) != expected[i]
        }
        # the kill provably landed mid-stream and the router resumed
        resumes = STREAM_RESUMES._values.get(("llmx",), 0.0) - resumes_before
        assert resumes > 0, "chaos plan never killed the hot replica"
        # the controller replaced the dead replica(s), counting them
        st = ray_tpu.get(
            ctrl.wait_status.remote("llmx", min_replicas=2, timeout_s=120),
            timeout=150,
        )
        assert st["replicas"] == 2, st
        assert st["restarts"]["death"] >= 1, st
        # the seeded plan reproduces the failure schedule from the seed
        # alone: identical consult sequence -> identical injections
        p1, p2 = ReplicaFaultPlan(SPEC, SEED), ReplicaFaultPlan(SPEC, SEED)
        phases = ["prefill"] * 3 + ["decode"] * 20
        s1 = [p1.consult(p) for p in phases]
        assert s1 == [p2.consult(p) for p in phases]
        assert p1.injections == 1 and ("kill_mid_decode", 6.0) in s1

        # -- ISSUE 15 acceptance: the SLO ledger on the SAME chaos run.
        # serve.slo_report() aggregates the replicas' log-bucket
        # histograms (p50/p99/p99.9 from summed counts — the thing the
        # old quantile gauges could never do), reconciles the intake
        # books exactly, and hands back the joined flight record of the
        # resumed requests with the failover stage named.
        rep = serve.slo_report()
        dep = rep["deployments"].get("llmx")
        assert dep, list(rep["deployments"])
        for key in ("ttft_s", "itl_s", "e2e_s"):
            blk = dep[key]
            assert blk["count"] > 0 and blk.get("p50") is not None, (key, blk)
            assert blk.get("p999") is not None, (key, blk)
        # books: every live engine balances exactly — chaos kills,
        # resumes, and cancels may not leak one unaccounted request
        # (finish→book increments quiesce within a beat of idle)
        deadline_b = time.monotonic() + 20
        while time.monotonic() < deadline_b and not dep.get("books_balanced"):
            time.sleep(0.5)
            rep = serve.slo_report()
            dep = rep["deployments"]["llmx"]
        assert dep["books_balanced"] is True, dep["books"]
        assert dep["books"], rep
        # goodput split from fault cost: the replayed tokens of every
        # resume were booked as fault, not goodput
        assert dep["goodput_tokens"] > 0, dep
        assert dep["fault_tokens"].get("resume_replay", 0) > 0, dep
        # flight recorder: a resumed request's joined record names the
        # failover stage and carries a resolvable trace id
        ours = [
            r for r in rep["flight_recorder"]
            if str(r["request_id"]).startswith("slo") and r["resumes"] > 0
        ]
        assert ours, [r["request_id"] for r in rep["flight_recorder"][:10]]
        rec = ours[0]
        assert rec["stages"].get("router.failover", 0) > 0, rec
        assert rec["slowest_stage"], rec
        assert rec.get("trace_id"), rec
        trace_ids = {
            (e.get("args") or {}).get("trace_id") for e in ray_tpu.timeline()
        }
        assert rec["trace_id"] in trace_ids, rec["trace_id"]
        # exact replay reconcile: the ledger entries this test created
        # sum to precisely what raytpu_stream_resume_replay_tokens_total
        # advanced by — same increments, observed via two sinks
        replay_delta = (
            STREAM_RESUME_REPLAY_TOKENS._values.get((), 0.0) - replay_before
        )
        led_new = [
            e for e in _slo.flight_recorder().snapshot()
            if e.get("tier") == "router"
            and e.get("request_id") not in led_before_ids
        ]
        assert replay_delta == sum(e["replayed_tokens"] for e in led_new), (
            replay_delta, [(e["request_id"], e["replayed_tokens"]) for e in led_new]
        )
        assert replay_delta > 0
    finally:
        GLOBAL_CONFIG.trace_sample_rate = 0.0
        GLOBAL_CONFIG.serve_affinity_weight = old_weight


@pytest.mark.chaos
def test_stalled_replica_health_restarted_and_stream_resumes(
    cfg, params, chaos_cluster
):
    """Health-restart tightening: a replica whose engine step loop
    STALLS (process alive, actor loop answering — liveness checks pass)
    is caught by the serve controller's replica.health() poll, killed
    with reason=unhealthy, and replaced; the interrupted stream resumes
    on the replacement and still delivers the exact sequence.

    Shares the module cluster, so its replicas carry the env kill plan
    too — deliberately survivable: the surgically-armed stall plan WINS
    over the env plan on the stalled replica (its kill never fires
    there), and the replacement's resumed tail is at most 6 decode
    consults, inside the env plan's 6-consult skip window."""
    ec = EngineConfig(
        num_blocks=64, block_size=8, prefill_buckets=(8, 32),
        decode_buckets=(1, 4), max_decode_batch=4,
        max_new_tokens_default=8,
        step_stall_unhealthy_s=1.0,  # fast wedge detection for the test
    )
    dep = serve.llm_deployment(
        cfg, engine=ec, name="llmst", num_replicas=1,
        route_prefix="/llmst", ray_actor_options={"num_cpus": 0.25},
    )
    handle = serve.run(dep.bind())
    ctrl = ray_tpu.get_actor("__serve_controller__")
    replicas = ray_tpu.get(ctrl.get_replicas.remote("llmst"), timeout=60)
    assert len(replicas) == 1
    # surgical plan on THE replica (not env-wide: the replacement
    # must come up clean): first consult stalls 30s, once
    ray_tpu.get(
        replicas[0].handle_request.remote(
            "testing_arm_replica_chaos", ["stall:1.0:30.0:1", 5], {}, ""
        ),
        timeout=60,
    )
    prompt = [4, 8, 1, 9]
    ref = InferenceEngine(cfg, params, ec).start()
    try:
        expected = list(ref.generate(prompt, max_new_tokens=6))
    finally:
        ref.stop()
    t0 = time.monotonic()
    toks = list(handle.stream(
        {"prompt": prompt, "max_new_tokens": 6},
        _method="generate", _timeout=180,
    ))
    assert toks == expected
    # the stream finished LONG before the 30s stall could have
    # released it — only a proactive restart explains that
    assert time.monotonic() - t0 < 28, "stream waited out the stall"
    st = ray_tpu.get(
        ctrl.wait_status.remote("llmst", min_replicas=1, timeout_s=60),
        timeout=90,
    )
    assert st["restarts"]["unhealthy"] >= 1, st
