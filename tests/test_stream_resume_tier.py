"""ISSUE 17 chaos gate: SIGKILL-mid-stream resume via the cluster-wide
KV prefix tier (the serve-E2E companion to tests/test_kv_tier.py, which
holds the unit/engine/server layers — these two tests are the only ones
needing a real cluster, so they live with the other stream-resume E2E
suites instead of paying cluster boot inside the alphabetically-early
kv-tier module).

* plan DISABLED: the hot replica is SIGKILLed mid-decode; every stream
  is byte-exact, the resumes go through TIER FAULT-IN (replay-token
  counter does NOT move), and the controller-spawned replacement boots
  WARM from the daemon tier registry;
* plan ARMED (missing_block prob 1.0): every survivor-side tier fetch
  fails, the counted fallback ladder lands on PR 10 prefix replay, and
  the streams are byte-exact anyway — reproducible from the one master
  chaos seed.
"""

import threading
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.util.chaos import KvTierFaultPlan, derive_plan_seed

pytest.importorskip("jax")

import jax  # noqa: E402

from ray_tpu.inference.engine import EngineConfig, InferenceEngine  # noqa: E402
from ray_tpu.inference.kv_cache import _chain_digest  # noqa: E402
from ray_tpu.models.llama import LlamaConfig, init_params  # noqa: E402

#: 24 tokens = 3 full blocks at block_size 8
SHARED = [12, 7, 3, 9, 1, 5, 2, 8] * 3


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.tiny()


@pytest.fixture(scope="module")
def params(cfg):
    return init_params(cfg, jax.random.PRNGKey(0))


def _digests(tokens, bs=8):
    """Full-block chain digests of ``tokens`` (the tier's key space)."""
    out, prev = [], b""
    for end in range(bs, len(tokens) + 1, bs):
        prev = _chain_digest(prev, tokens[end - bs : end])
        out.append(prev)
    return out


def _ec_cluster():
    return EngineConfig(
        num_blocks=64, block_size=8, prefill_buckets=(8, 32),
        decode_buckets=(1, 4), max_decode_batch=4,
        max_new_tokens_default=8, warmup=False,
    )


@pytest.fixture(scope="module")
def tier_cluster():
    ray_tpu.init(num_cpus=4)
    dep = serve.llm_deployment(
        LlamaConfig.tiny(), engine=_ec_cluster(), name="llmtier",
        num_replicas=2, kv_tier=True, route_prefix="/llmtier",
        ray_actor_options={"num_cpus": 0.25},
    )
    handle = serve.run(dep.bind())
    ctrl = ray_tpu.get_actor("__serve_controller__")
    ray_tpu.get(
        ctrl.wait_status.remote("llmtier", min_replicas=2, timeout_s=90),
        timeout=120,
    )
    yield handle
    serve.shutdown()
    ray_tpu.shutdown()


def _controller():
    return ray_tpu.get_actor("__serve_controller__")


def _replicas(name="llmtier"):
    return ray_tpu.get(_controller().get_replicas.remote(name), timeout=60)


def _replica_call(replica, method, args=(), timeout=60):
    return ray_tpu.get(
        replica.handle_request.remote(method, list(args), {}, ""),
        timeout=timeout,
    )


def _replica_metrics(replica) -> str:
    addr = _replica_call(replica, "metrics_address")
    return urllib.request.urlopen(
        f"http://{addr}/metrics", timeout=10
    ).read().decode()


def _scrape_total(name) -> float:
    """Sum a counter family across every live replica's /metrics."""
    total = 0.0
    for rep in _replicas():
        for line in _replica_metrics(rep).splitlines():
            if line.startswith(name) and " " in line:
                total += float(line.rsplit(" ", 1)[1])
    return total


def _warm_and_find_hot(handle, warm_prompt):
    """Serve one short warm request, let gossip land, and return the
    replica whose tier adverts GREW — the affinity-hot one."""
    from ray_tpu.core.config import GLOBAL_CONFIG

    before = {
        rep.actor_id:
            len(_replica_call(rep, "routing_stats").get("kv_tier") or {})
        for rep in _replicas()
    }
    list(handle.stream(
        {"prompt": warm_prompt, "max_new_tokens": 2},
        _method="generate", _timeout=120,
    ))
    time.sleep(3 * GLOBAL_CONFIG.serve_replica_stats_period_s)
    hot = [
        rep for rep in _replicas()
        if len(_replica_call(rep, "routing_stats").get("kv_tier") or {})
        > before.get(rep.actor_id, 0)
    ]
    assert len(hot) == 1, "warm request did not land on exactly one replica"
    return hot[0]


def _run_streams(handle, prompts, max_new, seed_base):
    results, errors = {}, {}

    def consume(i):
        try:
            results[i] = list(handle.stream(
                {"prompt": prompts[i], "max_new_tokens": max_new,
                 "temperature": 0.7, "seed": seed_base + i,
                 "request_id": f"tier{i}"},
                _method="generate", _timeout=180,
            ))
        except Exception as e:  # noqa: BLE001
            errors[i] = e

    threads = [
        threading.Thread(target=consume, args=(i,)) for i in prompts
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=240)
    return results, errors


@pytest.mark.chaos
def test_e2e_sigkill_mid_decode_resumes_via_tier_fault_in(
    tier_cluster, cfg, params
):
    """Chaos gate, plan DISABLED: the hot replica is SIGKILLed
    mid-decode under 4 concurrent streams. Every client gets the
    byte-exact sequence of an undisturbed run, the resumes went through
    TIER FAULT-IN — `raytpu_stream_resume_replay_tokens_total` does not
    grow (zero re-prefill of cached prefix), tier hit counters do —
    and the controller-spawned replacement comes up WARM (its tier
    adverts recovered from the daemon registry before serving)."""
    from ray_tpu.core.config import GLOBAL_CONFIG
    from ray_tpu.observability.rpc_metrics import (
        STREAM_RESUME_REPLAY_TOKENS, STREAM_RESUMES,
    )

    handle = tier_cluster
    n, max_new = 4, 12
    shared = SHARED
    prompts = {i: shared + [60 + i] for i in range(n)}
    ec = _ec_cluster()
    ref = InferenceEngine(cfg, params, ec).start()
    try:
        expected = {
            i: list(ref.generate(
                prompts[i], max_new_tokens=max_new,
                temperature=0.7, seed=100 + i,
            ))
            for i in range(n)
        }
    finally:
        ref.stop()

    old_weight = GLOBAL_CONFIG.serve_affinity_weight
    GLOBAL_CONFIG.serve_affinity_weight = 1e6
    try:
        # warm request prefill-publishes the 3 shared blocks and ticks
        # 2 decode consults on the hot replica's (about-to-be-armed)
        # kill window; its gossip pins the streams via affinity
        hot = _warm_and_find_hot(handle, shared + [42])
        # surgical kill plan on the HOT replica ONLY (the survivor and
        # the replacement must stay clean: this variant asserts replay
        # == 0, which a cascade of deaths could not guarantee): 6 more
        # decode consults, then SIGKILL — each stream has at most 7
        # delivered tokens, so the 24 shared-prefix tier tokens always
        # COVER the extended prompt (len <= 32 = 24 + block_size)
        _replica_call(
            hot, "testing_arm_replica_chaos", ["kill_mid_decode:1.0:4", 4242]
        )
        resumes_before = STREAM_RESUMES._values.get(("llmtier",), 0.0)
        replay_before = STREAM_RESUME_REPLAY_TOKENS._values.get((), 0.0)
        hits_before = _scrape_total("raytpu_kv_tier_hits_total")

        results, errors = _run_streams(handle, prompts, max_new, 100)
        assert not errors, errors
        assert results == expected, {
            i: (results.get(i), expected[i]) for i in range(n)
            if results.get(i) != expected[i]
        }
        resumes = (
            STREAM_RESUMES._values.get(("llmtier",), 0.0) - resumes_before
        )
        assert resumes > 0, "the kill never landed mid-stream"
        # THE tentpole assert: failover went through tier fault-in, so
        # the replay counter did not move — zero re-prefill of prefix
        # the cluster already had
        assert (
            STREAM_RESUME_REPLAY_TOKENS._values.get((), 0.0) - replay_before
            == 0.0
        )
        ctrl = _controller()
        st = ray_tpu.get(
            ctrl.wait_status.remote("llmtier", min_replicas=2, timeout_s=120),
            timeout=150,
        )
        assert st["replicas"] == 2 and st["restarts"]["death"] >= 1, st
        assert _scrape_total("raytpu_kv_tier_hits_total") > hits_before
        # warm replica restart: EVERY live replica — including the
        # replacement, which never served a shared-prefix request and
        # can only have recovered them from the daemon's tier registry
        # at boot — adverts the shared prefix chain
        chain = {d.hex() for d in _digests(shared)}
        for rep in _replicas():
            adverts = _replica_call(rep, "routing_stats").get("kv_tier") or {}
            assert chain <= set(adverts), (len(adverts), chain)
    finally:
        GLOBAL_CONFIG.serve_affinity_weight = old_weight


# slow: the in-gate equivalents are test_e2e_sigkill_mid_decode_resumes_
# via_tier_fault_in (same SIGKILL-mid-stream resume, tier path healthy)
# plus test_kv_tier.py::test_tier_fault_in_across_servers_byte_exact
# (the armed missing_block/corrupt_block ladder, counted fallback,
# byte-exact) and test_kv_tier.py::
# test_kv_tier_plan_derives_from_master_chaos_seed (schedule
# reproducibility) — this variant composes the three at full E2E cost
@pytest.mark.slow
@pytest.mark.chaos
def test_e2e_sigkill_with_armed_tier_chaos_falls_back_byte_exact(
    tier_cluster, cfg, params
):
    """Chaos gate, plan ARMED at prob 1.0: the same mid-decode SIGKILL,
    but every survivor-side tier fetch fails (missing_block). The
    fallback ladder is COUNTED and the streams land on PR 10 prefix
    replay — byte-exact either way, and the tier plan's schedule
    reproduces from the master chaos seed alone."""
    from ray_tpu.core.config import GLOBAL_CONFIG
    from ray_tpu.observability.rpc_metrics import STREAM_RESUMES

    handle = tier_cluster
    n, max_new = 4, 12
    shared = [9, 14, 6, 2, 11, 7, 13, 1] * 3  # fresh family: cold tier
    prompts = {i: shared + [80 + i] for i in range(n)}
    ec = _ec_cluster()
    ref = InferenceEngine(cfg, params, ec).start()
    try:
        expected = {
            i: list(ref.generate(
                prompts[i], max_new_tokens=max_new,
                temperature=0.7, seed=300 + i,
            ))
            for i in range(n)
        }
    finally:
        ref.stop()

    master = 20260806
    tier_seed = derive_plan_seed(master, "kv_tier")
    old_weight = GLOBAL_CONFIG.serve_affinity_weight
    GLOBAL_CONFIG.serve_affinity_weight = 1e6
    try:
        hot = _warm_and_find_hot(handle, shared + [43])
        # arm the tier plan on EVERY live replica (the resume target is
        # whichever survives), then the kill plan on the hot one
        for rep in _replicas():
            got = _replica_call(
                rep, "testing_arm_kv_tier_chaos",
                ["missing_block:1.0:0:99", tier_seed],
            )
            assert got == tier_seed
        _replica_call(
            hot, "testing_arm_replica_chaos", ["kill_mid_decode:1.0:4", 777]
        )
        resumes_before = STREAM_RESUMES._values.get(("llmtier",), 0.0)
        fb_before = _scrape_total("raytpu_kv_tier_fallbacks_total")

        results, errors = _run_streams(handle, prompts, max_new, 300)
        assert not errors, errors
        assert results == expected, {
            i: (results.get(i), expected[i]) for i in range(n)
            if results.get(i) != expected[i]
        }
        assert (
            STREAM_RESUMES._values.get(("llmtier",), 0.0) - resumes_before > 0
        )
        # the ladder fired and was counted on the survivor
        assert _scrape_total("raytpu_kv_tier_fallbacks_total") > fb_before
        # master-seed reproducibility: the armed seed derives from the
        # one logged master, and the derived plan's schedule is a pure
        # function of it
        p1 = KvTierFaultPlan("missing_block:1.0:0:99", tier_seed)
        p2 = KvTierFaultPlan(
            "missing_block:1.0:0:99", derive_plan_seed(master, "kv_tier")
        )
        phases = ["fault_in"] * 8
        s1 = [p1.consult(p) for p in phases]
        assert s1 == [p2.consult(p) for p in phases]
        assert ("missing_block", 0.0) in s1
    finally:
        GLOBAL_CONFIG.serve_affinity_weight = old_weight
        for rep in _replicas():
            try:
                _replica_call(rep, "testing_arm_kv_tier_chaos", ["", 0])
            except Exception:  # noqa: BLE001
                pass
