"""Streaming generators (``num_returns="streaming"``) — reference
``task_manager.h:102`` ObjectRefStream / ``_raylet.pyx:1345``."""

import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=2)
    yield
    ray_tpu.shutdown()


def test_streaming_local_mode():
    ray_tpu.init(local_mode=True)
    try:

        @ray_tpu.remote(num_returns="streaming")
        def gen(n):
            for i in range(n):
                yield i * 10

        vals = [ray_tpu.get(r) for r in gen.remote(5)]
        assert vals == [0, 10, 20, 30, 40]
    finally:
        ray_tpu.shutdown()


def test_streaming_basic(cluster):
    @ray_tpu.remote(num_returns="streaming")
    def stream(n):
        for i in range(n):
            yield {"i": i, "arr": np.full(8, i)}

    out = [ray_tpu.get(r, timeout=60) for r in stream.remote(6)]
    assert [o["i"] for o in out] == list(range(6))
    assert out[3]["arr"].sum() == 24


def test_streaming_consumes_before_completion(cluster):
    """Items are consumable WHILE the task runs — the defining property."""

    @ray_tpu.remote(num_returns="streaming")
    def slow():
        for i in range(4):
            time.sleep(0.5)
            yield i

    t0 = time.time()
    it = iter(slow.remote())
    first = ray_tpu.get(next(it), timeout=60)
    t_first = time.time() - t0
    rest = [ray_tpu.get(r, timeout=60) for r in it]
    t_all = time.time() - t0
    assert first == 0 and rest == [1, 2, 3]
    assert t_first < t_all - 0.8, (t_first, t_all)


def test_streaming_large_items_via_shm(cluster):
    @ray_tpu.remote(num_returns="streaming")
    def big_stream():
        for i in range(3):
            yield np.full(1 << 20, i, dtype=np.uint8)  # 1 MiB -> shm path

    for i, ref in enumerate(big_stream.remote()):
        arr = ray_tpu.get(ref, timeout=60)
        assert arr.shape == (1 << 20,) and int(arr[0]) == i


def test_streaming_error_mid_stream(cluster):
    @ray_tpu.remote(num_returns="streaming")
    def bad():
        yield 1
        yield 2
        raise ValueError("stream boom")

    it = iter(bad.remote())
    assert ray_tpu.get(next(it), timeout=60) == 1
    assert ray_tpu.get(next(it), timeout=60) == 2
    with pytest.raises(ray_tpu.RayTpuError):
        for _ in range(3):  # the failure lands on a subsequent next()
            next(it)


def test_streaming_empty(cluster):
    @ray_tpu.remote(num_returns="streaming")
    def empty():
        return
        yield  # pragma: no cover

    assert list(empty.remote()) == []


def test_streaming_feeds_downstream_tasks(cluster):
    """Stream item refs are first-class: pass them to other tasks."""

    @ray_tpu.remote(num_returns="streaming")
    def produce():
        for i in range(4):
            yield i

    @ray_tpu.remote
    def double(x):
        return 2 * x

    doubled = [double.remote(r) for r in produce.remote()]
    assert ray_tpu.get(doubled, timeout=120) == [0, 2, 4, 6]


def test_streaming_failed_dependency_raises(cluster):
    """A streaming task whose dependency failed must fail the stream,
    not hang the consumer (regression: empty return_ids swallowed
    pre-execution errors)."""

    @ray_tpu.remote
    def boom():
        raise ValueError("dep failed")

    @ray_tpu.remote(num_returns="streaming")
    def consume(dep):
        yield dep

    bad_ref = boom.remote()
    it = iter(consume.remote(bad_ref))
    with pytest.raises(ray_tpu.RayTpuError):
        next(it)


def test_streaming_actor_method(cluster):
    """Actor generator methods stream items exactly like normal tasks
    (reference: streaming generators on actors back Serve's token
    streaming, _raylet.pyx:1345)."""
    @ray_tpu.remote
    class A:
        def __init__(self):
            self.calls = 0

        def gen(self, n):
            self.calls += 1
            for i in range(n):
                yield i * 10

        def total(self):
            return self.calls

    a = A.remote()
    it = a.gen.options(num_returns="streaming").remote(4)
    vals = [ray_tpu.get(ref, timeout=30) for ref in it]
    assert vals == [0, 10, 20, 30]
    assert ray_tpu.get(a.total.remote(), timeout=30) == 1
    # second stream on the same (stateful) actor
    it2 = a.gen.options(num_returns="streaming").remote(2)
    assert [ray_tpu.get(r, timeout=30) for r in it2] == [0, 10]


def test_streaming_async_actor_generator(cluster):
    """Async-generator methods on concurrent actors stream too (the
    Serve replica shape)."""
    @ray_tpu.remote(max_concurrency=4)
    class A:
        async def agen(self, n):
            import asyncio as aio

            for i in range(n):
                await aio.sleep(0.01)
                yield f"tok{i}"

    a = A.remote()
    it = a.agen.options(num_returns="streaming").remote(3)
    assert [ray_tpu.get(r, timeout=30) for r in it] == ["tok0", "tok1", "tok2"]


def test_streaming_actor_mid_stream_error(cluster):
    @ray_tpu.remote
    class A:
        def gen(self):
            yield 1
            raise RuntimeError("boom mid-stream")

    a = A.remote()
    it = iter(a.gen.options(num_returns="streaming").remote())
    assert ray_tpu.get(next(it), timeout=30) == 1
    with pytest.raises(ray_tpu.RayTpuError):
        ray_tpu.get(next(it), timeout=30)


def test_producer_backpressure_bounds_owner_buffer(cluster):
    """A fast generator against a slow consumer keeps the owner-side
    buffer bounded by streaming_generator_backpressure_items (reference
    consumer-position protocol, task_manager.h:102)."""
    import time as _t

    from ray_tpu.core.api import _global_worker
    from ray_tpu.core.config import GLOBAL_CONFIG

    threshold = GLOBAL_CONFIG.streaming_generator_backpressure_items
    assert threshold > 0

    @ray_tpu.remote
    def gen(n):
        for i in range(n):
            yield i

    n = 2000
    g = gen.options(num_returns="streaming").remote(n)
    core = _global_worker().backend
    tid = g._task_id
    max_buffered = 0
    out = []
    for i, ref in enumerate(g):
        out.append(ray_tpu.get(ref, timeout=60))
        if i % 50 == 0:
            _t.sleep(0.02)  # slow consumer
            stream = core._streams.get(tid)
            if stream is not None:
                with stream._cond:
                    max_buffered = max(max_buffered, len(stream._items))
    assert out == list(range(n))
    # buffered backlog stays around the threshold (small slack for the
    # throttled consumed reports in flight)
    assert max_buffered <= threshold + threshold // 2 + 2, (
        max_buffered,
        threshold,
    )
