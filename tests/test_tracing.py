"""Distributed tracing + federated telemetry (ISSUE 9 acceptance).

Causal span chains: a sampled request must appear in ONE
``ray_tpu.timeline()`` dump as trace-linked spans crossing process
boundaries (driver → worker → worker; router → replica → engine), with
chrome-trace flow events connecting them. Federation: the controller
aggregates every node's metric registry with ``node`` labels in one
scrape. Sampling off (the default) must leave ZERO span records."""

import asyncio
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.observability import timeline
from ray_tpu.observability import tracing


@pytest.fixture(scope="module")
def traced_cluster():
    ray_tpu.init(
        num_cpus=4,
        num_nodes=2,
        system_config={"trace_sample_rate": 1.0},
    )
    yield
    ray_tpu.shutdown()
    # the driver process is shared across test modules: un-sample it
    GLOBAL_CONFIG.trace_sample_rate = 0.0


def _span_events(trace):
    return [
        e
        for e in trace
        if e.get("ph") == "X" and (e.get("args") or {}).get("trace_id")
    ]


def _traces_by_id(trace):
    out = {}
    for e in _span_events(trace):
        out.setdefault(e["args"]["trace_id"], []).append(e)
    return out


def _wait_for_trace(predicate, timeout_s=25.0):
    """Poll timeline() until the predicate passes (worker event chunks
    export every ~2s) — returns the passing dump."""
    deadline = time.time() + timeout_s
    last = []
    while time.time() < deadline:
        last = ray_tpu.timeline()
        if predicate(last):
            return last
        time.sleep(1.0)
    return last


def _cross_process_flow_links(trace, trace_id):
    """(s, f) flow pairs within one trace whose endpoints live in
    DIFFERENT processes — the Perfetto arrows the acceptance asks for."""
    spans = {
        e["args"]["span_id"]: e
        for e in _span_events(trace)
        if e["args"]["trace_id"] == trace_id
    }
    flow_ids = {
        int(sid[:12], 16): e
        for sid, e in spans.items()
        if e["args"].get("parent_span_id") in spans
    }
    links = []
    starts = {
        e["id"]: e for e in trace if e.get("ph") == "s" and e["id"] in flow_ids
    }
    for e in trace:
        if e.get("ph") == "f" and e.get("id") in starts:
            s = starts[e["id"]]
            if s["pid"] != e["pid"]:
                links.append((s, e))
    return links


def test_nested_task_trace_spans_three_processes(traced_cluster):
    timeline.clear_events()

    @ray_tpu.remote
    def inner(x):
        return x + 1

    @ray_tpu.remote
    def outer(x):
        # nested submit INSIDE a traced task: the child spec inherits
        # this task's span as its parent (causal chain, not a new root)
        return ray_tpu.get(inner.remote(x)) + 10

    assert ray_tpu.get(outer.remote(1), timeout=60) == 12

    def ok(trace):
        for tid, evs in _traces_by_id(trace).items():
            names = {e["name"] for e in evs}
            if any(n.startswith("task::") and n.endswith("outer") for n in names) and any(
                n.startswith("task::") and n.endswith("inner") for n in names
            ):
                if len({e["pid"] for e in evs}) >= 3:
                    return True
        return False

    trace = _wait_for_trace(ok)
    assert ok(trace), [
        (t, sorted({e["name"] for e in evs}))
        for t, evs in _traces_by_id(trace).items()
    ]
    # flow events draw the cross-process arrows
    tid = next(
        t
        for t, evs in _traces_by_id(trace).items()
        if any(e["name"].startswith("task::") and e["name"].endswith("inner") for e in evs)
    )
    assert _cross_process_flow_links(trace, tid), "no cross-process flow pairs"


def test_actor_call_inherits_trace(traced_cluster):
    timeline.clear_events()

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert ray_tpu.get(c.bump.remote(), timeout=60) == 1

    def ok(trace):
        for _tid, evs in _traces_by_id(trace).items():
            names = {e["name"] for e in evs}
            if any(n.startswith("submit::") and n.endswith("bump") for n in names) and any(
                n.startswith("task::") and n.endswith("bump") for n in names
            ):
                return len({e["pid"] for e in evs}) >= 2
        return False

    trace = _wait_for_trace(ok)
    assert ok(trace)


def test_serve_streaming_trace(traced_cluster):
    from ray_tpu import serve

    timeline.clear_events()

    @serve.deployment
    class Echo:
        def gen(self, n):
            for i in range(n):
                yield i

    handle = serve.run(Echo.bind())
    try:
        assert list(handle.stream(3, _method="gen", _timeout=60)) == [0, 1, 2]

        def ok(trace):
            for _tid, evs in _traces_by_id(trace).items():
                names = {e["name"] for e in evs}
                if any(n.startswith("serve::Echo") for n in names) and any(
                    "handle_request_streaming" in n for n in names
                ):
                    return len({e["pid"] for e in evs}) >= 2
            return False

        trace = _wait_for_trace(ok)
        assert ok(trace), [
            sorted({e["name"] for e in evs})
            for evs in _traces_by_id(trace).values()
        ]
    finally:
        serve.delete("Echo")


def test_stage_histograms_and_cluster_status(traced_cluster):
    from ray_tpu.observability.metrics import render
    from ray_tpu.util import state

    @ray_tpu.remote
    def noop():
        return None

    ray_tpu.get([noop.remote() for _ in range(10)], timeout=60)

    # owner-side stage histograms measured, not inferred
    text = render()
    assert "raytpu_task_stage_seconds_bucket" in text
    stages = {
        line.split('stage="')[1].split('"')[0]
        for line in text.splitlines()
        if line.startswith("raytpu_task_stage_seconds") and 'stage="' in line
    }
    assert {"queue", "lease", "push", "total"} <= stages, stages

    # cluster_status reflects live nodes/tasks within one poll period
    cs = ray_tpu.cluster_status()
    assert len(cs["nodes"]) == 2
    assert len(cs["objects"]) == 2  # per-node store stats synced
    deadline = time.time() + 10
    while time.time() < deadline:
        cs = ray_tpu.cluster_status()
        if cs["tasks"]["summary"].get("FINISHED", 0) >= 1:
            break
        time.sleep(0.5)
    assert cs["tasks"]["summary"].get("FINISHED", 0) >= 1
    assert state.cluster_status()["nodes"] == cs["nodes"]


def test_federation_scrape_returns_every_node(traced_cluster):
    from ray_tpu.util import state

    tel = state.cluster_telemetry()
    # every registered node answered with raytpu_* series
    assert len(tel["nodes"]) == 2
    for text in tel["nodes"].values():
        assert "raytpu_object_store_used_bytes" in text
    assert "raytpu_" in tel["controller"]
    # the merged /federate view stamps node labels on every series
    fed = urllib.request.urlopen(
        f"http://127.0.0.1:{tel['federate_port']}/federate", timeout=30
    ).read().decode()
    labels = set()
    for line in fed.splitlines():
        if line.startswith("raytpu_") and 'node="' in line:
            # daemon gauges already carry a node label: injection must
            # NOT duplicate the label name (a Prometheus parse error)
            assert line.count('node="') == 1, line
            labels.add(line.split('node="')[1].split('"')[0])
    node_hexes = {h[:12] for h in tel["nodes"]}
    assert node_hexes <= labels, (node_hexes, labels)
    assert "controller" in labels
    # TYPE comments are deduped so strict parsers don't choke
    type_lines = [l for l in fed.splitlines() if l.startswith("# TYPE ")]
    assert len(type_lines) == len({" ".join(l.split()[:3]) for l in type_lines})


def test_e2e_llm_serve_and_nested_chain_traces(traced_cluster):
    """ISSUE 9 acceptance: ONE timeline dump where a serve LLM request
    (ingress task → router dispatch → replica push → engine spans) and a
    nested ``f.remote()`` chain EACH appear as causally-linked spans
    spanning >= 3 distinct processes, flow-connected."""
    pytest.importorskip("jax")
    from ray_tpu import serve
    from ray_tpu.inference.engine import EngineConfig
    from ray_tpu.models.llama import LlamaConfig

    timeline.clear_events()
    cfg = LlamaConfig.tiny()
    ec = EngineConfig(
        num_blocks=32, block_size=8, prefill_buckets=(8,),
        decode_buckets=(1, 2), max_decode_batch=2,
        max_new_tokens_default=4,
    )
    dep = serve.llm_deployment(
        cfg, engine=ec, num_replicas=1, ray_actor_options={"num_cpus": 0.5}
    )
    handle = serve.run(dep.bind())
    try:
        @ray_tpu.remote
        def llm_ingress(h):
            # proxy-tier shape: the serve call happens OFF the driver, so
            # the request chain crosses driver → ingress worker → replica
            return len(
                list(
                    h.stream(
                        {"prompt": [1, 2, 3, 4], "max_new_tokens": 4},
                        _method="generate",
                        _timeout=120,
                    )
                )
            )

        assert ray_tpu.get(llm_ingress.remote(handle), timeout=240) >= 1

        @ray_tpu.remote
        def inner(x):
            return x + 1

        @ray_tpu.remote
        def outer(x):
            return ray_tpu.get(inner.remote(x))

        assert ray_tpu.get(outer.remote(5), timeout=60) == 6

        def ok(trace):
            llm_ok = chain_ok = False
            for tid, evs in _traces_by_id(trace).items():
                names = {e["name"] for e in evs}
                pids = {e["pid"] for e in evs}
                if (
                    any(n == "llm_request" for n in names)
                    and any(n.startswith("serve::") for n in names)
                    and len(pids) >= 3
                    and _cross_process_flow_links(trace, tid)
                ):
                    llm_ok = True
                if (
                    any(n.startswith("task::") and n.endswith("inner") for n in names)
                    and len(pids) >= 3
                    and _cross_process_flow_links(trace, tid)
                ):
                    chain_ok = True
            return llm_ok and chain_ok

        trace = _wait_for_trace(ok, timeout_s=40.0)
        assert ok(trace), [
            (len({e["pid"] for e in evs}), sorted({e["name"] for e in evs}))
            for evs in _traces_by_id(trace).values()
        ]
    finally:
        serve.shutdown()


# ---------------------------------------------------------------------------
# cluster-free units


def test_rpc_meta_carries_trace_and_records_server_span():
    from ray_tpu.core import rpc

    async def run():
        seen = {}
        server = rpc.RpcServer()

        async def work(payload, conn):
            seen["wire"] = tracing.current_wire()
            return "ok"

        server.register("work", work)
        port = await server.start()
        client = rpc.RpcClient("127.0.0.1", port)
        try:
            with tracing.scope(("t" * 24, "s" * 16)):
                assert await client.call("work", {}) == "ok"
            # traced: the handler ran inside the caller's trace and the
            # server recorded an rpc:: span parented to the sent span
            assert seen["wire"] is not None
            assert seen["wire"][0] == "t" * 24
            assert seen["wire"][1] != "s" * 16  # a CHILD span, not the parent
            # untraced call: no ambient context server-side
            assert await client.call("work", {}) == "ok"
            assert seen["wire"] is None
        finally:
            await client.close()
            await server.stop()

    asyncio.new_event_loop().run_until_complete(run())
    evs = [
        e
        for e in timeline.timeline_events()
        if e.name == "rpc::work" and (e.args or {}).get("trace_id") == "t" * 24
    ]
    assert len(evs) == 1
    assert evs[0].args["parent_span_id"] == "s" * 16


def test_sampling_off_leaves_zero_spans():
    """The hot-path guarantee: with rate 0 and no ambient context,
    stamping/span entry points record nothing and allocate no ids."""
    old = GLOBAL_CONFIG.trace_sample_rate
    GLOBAL_CONFIG.trace_sample_rate = 0.0  # module fixture runs at 1.0
    try:
        timeline.clear_events()

        class _Spec:
            name = "noop"
            trace_ctx = None

            class task_id:
                @staticmethod
                def hex():
                    return "00" * 8

        spec = _Spec()
        tracing.stamp_spec(spec)
        assert spec.trace_ctx is None
        with tracing.span("should-not-record") as ctx:
            assert ctx is None
        with tracing.root_span("should-not-record-either") as ctx:
            assert ctx is None
        assert tracing.current_wire() is None
        assert not [
            e
            for e in timeline.timeline_events()
            if (e.args or {}).get("trace_id") or e.category == "trace"
        ]
    finally:
        GLOBAL_CONFIG.trace_sample_rate = old


def test_timeline_export_retention_bounded():
    """Controller-side export table: byte budget drops oldest chunks,
    same-key re-export is idempotent, a dead node's chunks are reaped."""
    from ray_tpu.core.controller import Controller, NodeInfo

    async def run():
        c = Controller()
        old = GLOBAL_CONFIG.timeline_kv_max_bytes
        GLOBAL_CONFIG.timeline_kv_max_bytes = 1000
        try:
            for i in range(10):
                await c.c_export_events(
                    {"key": f"n1:{i}", "blob": b"x" * 300, "node_id": b"n1"},
                    None,
                )
            blobs = await c.c_collect_events({}, None)
            assert len(blobs) <= 3  # 1000 // 300
            assert c._timeline_export_bytes <= 1000
            # oldest-first: the survivors are the NEWEST chunks
            assert set(c.timeline_exports) == {"n1:7", "n1:8", "n1:9"}
            # re-export of an existing key replaces, never duplicates
            await c.c_export_events(
                {"key": "n1:9", "blob": b"y" * 300, "node_id": b"n1"}, None
            )
            assert c._timeline_export_bytes <= 1000
            assert c.timeline_exports["n1:9"][1] == b"y" * 300
            # a single oversized chunk is kept while alone (never
            # self-evicts into an empty table)
            await c.c_export_events(
                {"key": "big", "blob": b"z" * 5000, "node_id": b"n2"}, None
            )
            assert "big" in c.timeline_exports
            # node death reaps that node's chunks
            node = NodeInfo(
                node_id=b"n2", host="127.0.0.1", port=1, total={}, available={}
            )
            c.nodes[b"n2"] = node
            await c._mark_node_dead(node, "test")
            assert "big" not in c.timeline_exports
            assert all(nid != b"n2" for nid, _b in c.timeline_exports.values())
        finally:
            GLOBAL_CONFIG.timeline_kv_max_bytes = old

    asyncio.new_event_loop().run_until_complete(run())
