"""JaxTrainer end-to-end tests (CPU, multi-process worker gang).

Reference test model: ``train/tests/test_data_parallel_trainer.py``.
XLA cross-process collectives don't run on CPU in CI, so the 2-worker
data-parallel test syncs gradients through the object-store collective
group — the orchestration path (gang PG, session, report, checkpoints,
failure restart) is identical to the TPU case, where sync happens inside
the compiled program over ICI instead.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.train import (
    Checkpoint,
    CheckpointConfig,
    CheckpointManager,
    FailureConfig,
    JaxBackendConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
    TrainingFailedError,
)
from ray_tpu import train


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def _dp_train_fn(config):
    """Linear regression, data-parallel over the object store."""
    from ray_tpu.parallel.collectives import CollectiveGroup

    ctx = train.get_context()
    rank, world = ctx.get_world_rank(), ctx.get_world_size()
    group = (
        CollectiveGroup(f"train-{ctx.get_experiment_name()}", world, rank)
        if world > 1
        else None
    )
    rng = np.random.RandomState(100 + rank)
    w_true = np.array([2.0, -3.0])
    w = np.zeros(2)
    start = 0
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        state = ckpt.to_dict()
        w, start = state["w"], state["step"]
    for step in range(start, config["steps"]):
        X = rng.randn(32, 2)
        y = X @ w_true
        grad = -2 * X.T @ (y - X @ w) / 32
        if group is not None:
            grad = group.allreduce(grad, op="mean")
        w = w - 0.2 * grad
        loss = float(((y - X @ w) ** 2).mean())
        out_ckpt = None
        if rank == 0 and (step + 1) % 5 == 0:
            out_ckpt = Checkpoint.from_dict({"w": w, "step": step + 1})
        if config.get("crash_at") is not None and step == config["crash_at"] and ckpt is None:
            raise RuntimeError("injected worker failure")
        train.report({"loss": loss, "step": step}, checkpoint=out_ckpt)


@pytest.mark.slow
def test_two_worker_dp_loss_goes_down(cluster, tmp_path):
    trainer = JaxTrainer(
        _dp_train_fn,
        train_loop_config={"steps": 12},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="dp2", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    losses = [m["loss"] for m in result.metrics_history]
    assert len(losses) == 12
    assert losses[-1] < losses[0] * 0.5, losses
    assert result.metrics["step"] == 11
    assert result.checkpoint is not None
    state = result.checkpoint.to_dict()
    np.testing.assert_allclose(state["w"], [2.0, -3.0], atol=0.5)


def test_failure_restart_resumes_from_checkpoint(cluster, tmp_path):
    trainer = JaxTrainer(
        _dp_train_fn,
        train_loop_config={"steps": 10, "crash_at": 7},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="ft",
            storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1),
        ),
    )
    result = trainer.fit()
    # crashed at step 7 on attempt 1 (checkpoint was at step 5), resumed
    # from step 5 and ran to completion
    assert result.metrics["step"] == 9
    assert result.checkpoint.to_dict()["step"] == 10


def test_failure_exhausts_max_failures(cluster, tmp_path):
    def always_crash(config):
        raise ValueError("boom")

    trainer = JaxTrainer(
        always_crash,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="crash", storage_path=str(tmp_path)),
    )
    with pytest.raises(TrainingFailedError, match="boom"):
        trainer.fit()


def test_checkpoint_manager_retention(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "run"), num_to_keep=2,
                            score_attribute="acc", score_order="max")
    paths = []
    for i, acc in enumerate([0.1, 0.9, 0.3, 0.2]):
        c = mgr.register(Checkpoint.from_dict({"i": i}), {"acc": acc})
        paths.append(c.path)
    assert len(mgr.registered) == 2
    # best (acc=0.9) survives retention; latest is the last registered
    assert mgr.best().to_dict()["i"] == 1
    assert mgr.latest().to_dict()["i"] == 3


def test_checkpoint_manager_restore(tmp_path):
    run = str(tmp_path / "run")
    mgr = CheckpointManager(run)
    mgr.register(Checkpoint.from_dict({"step": 1}), {"loss": 1.0})
    mgr2 = CheckpointManager.restore(run)
    assert mgr2.latest().to_dict()["step"] == 1
    mgr2.register(Checkpoint.from_dict({"step": 2}), {"loss": 0.5})
    assert mgr2.latest().to_dict()["step"] == 2


def test_mesh_and_sharding_rules_session_plumbing():
    """JaxBackendConfig.mesh_spec/sharding → context metadata →
    train.get_mesh()/get_sharding_rules() (ISSUE 14 unified-plan
    delivery). Session-level, no cluster: the trainer serializes the
    spec as plain dataclass fields, the session rebuilds the mesh over
    the worker's global devices."""
    from dataclasses import asdict

    import jax

    from ray_tpu.parallel.mesh import FSDP, MeshSpec
    from ray_tpu.train.session import TrainContext, _end_session, _start_session

    ctx = TrainContext(
        metadata={
            "mesh_spec": asdict(MeshSpec(fsdp=-1)),
            "sharding_rules": "fsdp",
        }
    )
    _start_session(ctx)
    try:
        mesh = train.get_mesh()
        assert mesh is not None
        assert mesh.shape[FSDP] == len(jax.devices())  # -1 resolved globally
        rules = train.get_sharding_rules()
        assert rules["embed"] == FSDP and rules["batch"] is not None
        # unconfigured keys degrade to None, unknown table names raise
        ctx.metadata.pop("mesh_spec")
        assert train.get_mesh() is None
        ctx.metadata["sharding_rules"] = "zigzag"
        with pytest.raises(ValueError, match="zigzag"):
            train.get_sharding_rules()
    finally:
        _end_session()


def test_trainer_threads_mesh_spec_into_contexts(cluster, tmp_path):
    """The trainer delivers the SAME plan to every rank (metadata is
    per-rank copied, not shared)."""
    from ray_tpu.parallel.mesh import MeshSpec

    def loop(config=None):
        ctx = train.get_context()
        spec = ctx.metadata.get("mesh_spec")
        train.report(
            {
                "rank": ctx.get_world_rank(),
                "spec_fsdp": spec["fsdp"] if spec else None,
                "rules": ctx.metadata.get("sharding_rules"),
            }
        )

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        backend_config=JaxBackendConfig(
            distributed=False, platform="cpu",
            mesh_spec=MeshSpec(fsdp=-1), sharding="fsdp",
        ),
        run_config=RunConfig(name="mesh-plumb", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.metrics["spec_fsdp"] == -1
    assert result.metrics["rules"] == "fsdp"


def test_scaling_config_topology_bundles():
    sc = ScalingConfig(topology="v4-32", use_tpu=True)
    assert sc.resolved_num_workers() == 4
    bundles = sc.bundles()
    assert len(bundles) == 4
    assert all(b["TPU"] == 4.0 for b in bundles)
    assert bundles[0]["TPU-v4-32-head"] == 1.0
    assert sc.pg_strategy() == "STRICT_SPREAD"
