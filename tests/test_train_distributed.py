"""The distributed JaxBackend path: real 2-process
``jax.distributed.initialize`` through WorkerGroup on CPU (the gang
bootstrap the TPU path uses, minus the chips), plus multi-slice mesh
helpers. Reference: ``train/torch/config.py:66-116`` rendezvous."""

import time

import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import JaxBackendConfig, JaxTrainer, RunConfig, ScalingConfig

from conftest import multiprocess_cpu_collectives


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def _dist_fn(config):
    """Runs in each worker AFTER jax.distributed.initialize (setup_fn)."""
    import jax
    import numpy as np

    ctx = train.get_context()
    world = ctx.get_world_size()
    # the rendezvous worked: every process sees the whole gang
    assert jax.process_count() == world, (jax.process_count(), world)
    local = jax.local_device_count()
    total = jax.device_count()
    assert total == world * local
    # a real cross-process collective: allgather each rank's value
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(
        np.array([ctx.get_world_rank()], np.int32)
    )
    assert sorted(int(v) for v in gathered.ravel()) == list(range(world))
    train.report(
        {
            "procs": jax.process_count(),
            "devices": total,
            "rank": ctx.get_world_rank(),
        }
    )


@multiprocess_cpu_collectives
def test_two_process_jax_distributed(cluster, tmp_path):
    trainer = JaxTrainer(
        _dist_fn,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=2),
        backend_config=JaxBackendConfig(
            distributed=True,
            platform="cpu",
            extra_env={"XLA_FLAGS": "--xla_force_host_platform_device_count=2"},
        ),
        run_config=RunConfig(name="dist-jax", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.metrics["procs"] == 2
    assert result.metrics["devices"] == 4  # 2 procs x 2 virtual cpu devices


def _dist_ckpt_fn(config):
    import jax

    ctx = train.get_context()
    assert jax.process_count() == ctx.get_world_size()
    start = 0
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        start = ckpt.to_dict()["step"]
    for step in range(start, 4):
        if step == 2 and train.get_checkpoint() is None:
            # first attempt (no checkpoint yet): the whole gang dies at
            # step 2; the retry resumes from the step-2 checkpoint
            raise RuntimeError("boom at step 2 (first attempt)")
        from ray_tpu.train import Checkpoint

        train.report(
            {"step": step, "procs": jax.process_count()},
            checkpoint=Checkpoint.from_dict({"step": step + 1})
            if ctx.get_world_rank() == 0
            else None,
        )


def test_distributed_worker_failure_restarts_gang(cluster, tmp_path):
    """Rank 1 dies mid-training: the whole gang restarts from the last
    checkpoint and jax.distributed re-initializes cleanly."""
    from ray_tpu.train import FailureConfig

    trainer = JaxTrainer(
        _dist_ckpt_fn,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=2),
        backend_config=JaxBackendConfig(
            distributed=True,
            platform="cpu",
            extra_env={"XLA_FLAGS": "--xla_force_host_platform_device_count=2"},
        ),
        run_config=RunConfig(
            name="dist-restart",
            storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1),
        ),
    )
    result = trainer.fit()
    assert result.metrics["step"] == 3  # completed all steps post-restart
    assert result.metrics["procs"] == 2


def test_slice_topology_mesh():
    """Multi-slice mesh helper: data axis spans slices (DCN), the rest
    stays inside a slice (ICI)."""
    from ray_tpu.parallel.mesh import (
        DATA,
        FSDP,
        MeshSpec,
        cpu_mesh_devices,
        slice_topology_mesh,
    )

    mesh = slice_topology_mesh(
        2, MeshSpec(fsdp=4), devices=cpu_mesh_devices(8)
    )
    assert mesh.shape[DATA] == 2  # one data rank per slice
    assert mesh.shape[FSDP] == 4

    mesh2 = slice_topology_mesh(
        4, MeshSpec(fsdp=-1), devices=cpu_mesh_devices(8)
    )
    assert mesh2.shape[DATA] == 4
    assert mesh2.shape[FSDP] == 2
