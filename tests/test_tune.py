"""ray_tpu.tune tests: search spaces, ASHA, the controller e2e, and
trainer-as-trainable (reference test model: ``tune/tests/``)."""

import time

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune.schedulers import CONTINUE, STOP


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_generate_variants_grid_product():
    from ray_tpu.tune.search import generate_variants

    vs = generate_variants(
        {"a": tune.grid_search([1, 2, 3]), "b": tune.grid_search(["x", "y"]), "c": 7}
    )
    assert len(vs) == 6
    assert all(v["c"] == 7 for v in vs)
    assert {(v["a"], v["b"]) for v in vs} == {(a, b) for a in (1, 2, 3) for b in ("x", "y")}


def test_generate_variants_samplers_and_num_samples():
    from ray_tpu.tune.search import generate_variants

    vs = generate_variants(
        {"lr": tune.loguniform(1e-4, 1e-1), "bs": tune.choice([16, 32])},
        num_samples=8,
        seed=0,
    )
    assert len(vs) == 8
    assert all(1e-4 <= v["lr"] <= 1e-1 for v in vs)
    assert all(v["bs"] in (16, 32) for v in vs)
    # nested spaces resolve too
    vs2 = generate_variants({"opt": {"lr": tune.uniform(0, 1)}, "k": 3}, seed=1)
    assert 0 <= vs2[0]["opt"]["lr"] <= 1


def test_asha_scheduler_unit():
    """Deterministic ASHA behavior: at a rung, values below the top-1/rf
    cutoff stop."""
    asha = tune.ASHAScheduler(mode="max", max_t=64, grace_period=4, reduction_factor=2)
    assert asha.on_result("a", 4, 100.0) == CONTINUE  # first at rung: no peers
    assert asha.on_result("b", 4, 50.0) == STOP  # below cutoff (100)
    assert asha.on_result("c", 4, 150.0) == CONTINUE  # above
    # min mode flips comparisons
    asha_min = tune.ASHAScheduler(mode="min", max_t=64, grace_period=4, reduction_factor=2)
    assert asha_min.on_result("a", 4, 1.0) == CONTINUE
    assert asha_min.on_result("b", 4, 5.0) == STOP


def _objective(config):
    lr = config["lr"]
    for step in range(1, 16):
        tune.report({"score": lr * step, "step": step})
        time.sleep(0.005)


def test_grid_search_e2e(cluster):
    tuner = tune.Tuner(
        _objective,
        param_space={"lr": tune.grid_search([0.1, 1.0, 5.0, 10.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max", max_concurrent_trials=4),
        resources_per_trial={"CPU": 0.5},
    )
    grid = tuner.fit()
    assert len(grid) == 4
    best = grid.get_best_result()
    assert best.config["lr"] == 10.0
    assert best.metrics["score"] == 10.0 * 15
    assert all(r.status == "TERMINATED" for r in grid)


def test_asha_stops_underperformers_e2e(cluster):
    """8 trials under ASHA: descending lr order guarantees later (worse)
    trials fall below the rung cutoff and are killed early."""
    asha = tune.ASHAScheduler(mode="max", max_t=16, grace_period=2, reduction_factor=2)
    lrs = [16.0, 8.0, 4.0, 2.0, 1.0, 0.5, 0.2, 0.1]
    tuner = tune.Tuner(
        _objective,
        param_space={"lr": tune.grid_search(lrs)},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", scheduler=asha, max_concurrent_trials=2
        ),
        resources_per_trial={"CPU": 0.5},
    )
    grid = tuner.fit()
    assert len(grid) == 8
    stopped = [r for r in grid if r.status == "STOPPED"]
    assert stopped, "ASHA must early-stop underperformers"
    assert grid.get_best_result().config["lr"] == 16.0
    # the best trial ran to completion
    assert next(r for r in grid if r.config["lr"] == 16.0).status == "TERMINATED"


def test_errored_trial_reported(cluster):
    def bad(config):
        if config["x"] == 1:
            raise ValueError("boom")
        tune.report({"ok": 1})

    grid = tune.Tuner(
        bad,
        param_space={"x": tune.grid_search([0, 1])},
        tune_config=tune.TuneConfig(metric="ok", mode="max"),
    ).fit()
    assert len(grid.errors) == 1
    assert "boom" in grid.errors[0].error
    assert grid.get_best_result().config["x"] == 0


def test_trainer_as_trainable(cluster):
    """JaxTrainer launched per-trial: the variant config merges into the
    train loop config (reference train/base_trainer.py:608)."""
    from ray_tpu import train as rt_train
    from ray_tpu.train import JaxBackendConfig, JaxTrainer, RunConfig, ScalingConfig

    def train_fn(config):
        rt_train.report({"loss": 1.0 / config["lr"]})

    trainer = JaxTrainer(
        train_fn,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        backend_config=JaxBackendConfig(distributed=False),
        run_config=RunConfig(name="tune-trial"),
    )
    grid = tune.Tuner(
        trainer,
        param_space={"lr": tune.grid_search([1.0, 2.0, 4.0])},
        tune_config=tune.TuneConfig(metric="loss", mode="min", max_concurrent_trials=1),
    ).fit()
    assert len(grid) == 3
    best = grid.get_best_result()
    assert best.config["lr"] == 4.0
    assert best.metrics["loss"] == 0.25


def test_pbt_exploits_and_mutates(cluster):
    """PBT: a bottom-quantile trial restarts from a top peer's checkpoint
    with a mutated config mid-training (reference schedulers/pbt.py)."""

    def trainable(config):
        # resume from an exploited checkpoint if one was handed to us
        ck = tune.get_checkpoint()
        step = ck["step"] if ck else 0
        score = ck["score"] if ck else 0.0
        while step < 16:
            step += 1
            score += config["lr"]  # higher lr -> faster score growth
            tune.report(
                {"score": score, "lr": config["lr"]},
                checkpoint={"step": step, "score": score},
            )
            # slow enough that the controller interleaves the two trials'
            # reports (PBT decisions need a live population)
            time.sleep(0.1)

    scheduler = tune.PopulationBasedTraining(
        perturbation_interval=3,
        quantile_fraction=0.5,
        hyperparam_mutations={"lr": lambda: 1.0},
        seed=7,
    )
    tuner = tune.Tuner(
        trainable,
        param_space={"lr": tune.grid_search([0.1, 1.0])},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", scheduler=scheduler,
            max_concurrent_trials=2,
        ),
    )
    grid = tuner.fit()
    assert len(grid) == 2
    # the weak trial (lr=0.1) must have been exploited at least once:
    # its reported lr changes mid-history OR its score jumps to the
    # strong trial's trajectory
    exploited = False
    for r in grid:
        lrs = {m["lr"] for m in r.metrics_history if "lr" in m}
        if len(lrs) > 1:
            exploited = True
    assert exploited, [
        [m.get("lr") for m in r.metrics_history] for r in grid
    ]


def test_experiment_snapshot_and_resume(cluster, tmp_path):
    """Kill-and-resume: a snapshot taken mid-sweep restores finished
    results and restarts unfinished trials from their checkpoints
    (reference execution/experiment_state.py)."""
    from ray_tpu.train import RunConfig

    marker = tmp_path / "slow_mode"
    marker.write_text("on")

    def trainable(config):
        ck = tune.get_checkpoint()
        start = ck["i"] if ck else 0
        import os as _os

        for i in range(start, 6):
            tune.report(
                {"i": i, "x": config["x"], "start": start},
                checkpoint={"i": i + 1},
            )
            # BARRIER, not pacing (deflake): while the marker exists the
            # first run PARKS after each checkpointed report, so the
            # mid-run snapshot capture below cannot race trial progress
            # on a loaded box (the PR 1/PR 4 residual timing flake — a
            # fixed per-report sleep let fast trials finish before a
            # resumable snapshot existed). The test removes the marker
            # once it has its copy; the cap bounds a capture failure.
            waited = 0.0
            while _os.path.exists(str(config["marker"])) and waited < 20.0:
                time.sleep(0.1)
                waited += 0.1

    run_config = RunConfig(name="resume_exp", storage_path=str(tmp_path))
    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2, 3, 4]), "marker": str(marker)},
        tune_config=tune.TuneConfig(metric="x", mode="max", max_concurrent_trials=2),
        run_config=run_config,
    )

    # simulate a driver crash: run fit() in a thread and abandon it
    import threading

    done = threading.Event()

    def doomed():
        try:
            tuner.fit()
        except BaseException:
            pass
        finally:
            done.set()

    t = threading.Thread(target=doomed, daemon=True)
    t.start()
    snap = tmp_path / "resume_exp" / "tuner.pkl"

    # Capture a MID-RUN snapshot that actually EXERCISES resume: at least
    # one unfinished trial with a saved checkpoint. (Deflake, round-5
    # verdict: a fixed 2s sleep raced trial progress on a loaded box — a
    # too-early copy held no checkpoints, so the resumed run restarted
    # every trial from scratch and the start>0 assertion failed.) The
    # validation runs on the COPY, so the live file terminating between
    # check and copy cannot invalidate the captured state.
    import shutil

    import cloudpickle

    crash_dir = tmp_path / "crash_copy"
    crash_dir.mkdir()
    copied = crash_dir / "tuner.pkl"

    def _copy_is_resumable() -> bool:
        if not snap.exists():
            return False
        shutil.copy(snap, copied)
        try:
            with open(copied, "rb") as f:  # atomic writes: no partial reads
                state = cloudpickle.load(f)
        except Exception:
            return False  # raced os.replace — retry
        return any(
            tr.status in ("PENDING", "RUNNING") and tr.last_checkpoint is not None
            for tr in state.get("trials", [])
        )

    deadline = time.time() + 90
    captured = False
    while time.time() < deadline and not captured:
        captured = _copy_is_resumable()
        if not captured:
            time.sleep(0.1)
    assert captured, "no mid-run snapshot with a checkpointed trial appeared"

    marker.unlink()  # fast mode for the resumed run
    done.wait(timeout=120)  # let the doomed run finish to free actors

    restored = tune.Tuner.restore(str(crash_dir), trainable)
    grid = restored.fit()
    assert len(grid) == 4
    assert sorted(r.metrics["i"] for r in grid) == [5, 5, 5, 5]
    # the resume path must actually have run: at least one trial was
    # restarted FROM A CHECKPOINT (its post-resume reports carry start>0)
    resumed_starts = [
        m["start"]
        for r in grid
        for m in r.metrics_history
        if m.get("start", 0) > 0
    ]
    assert resumed_starts, "no trial resumed from a checkpoint"


def _surrogate_objective(config):
    """Smooth 2-d surrogate with optimum at (0.3, -0.5), plus a
    categorical that shifts the optimum (the searcher must learn all
    three dims)."""
    from ray_tpu import tune

    x, y = config["x"], config["y"]
    bonus = 0.5 if config["kind"] == "good" else 0.0
    score = -((x - 0.3) ** 2) - ((y + 0.5) ** 2) + bonus
    tune.report(score=score)


@pytest.mark.slow
def test_tpe_beats_random_on_surrogate(cluster):
    """Seeded head-to-head (the reference's searcher-quality test
    shape): TPE must find a better optimum than random search under the
    same trial budget."""
    from ray_tpu import tune
    from ray_tpu.tune import TuneConfig, Tuner

    space = {
        "x": tune.uniform(-2.0, 2.0),
        "y": tune.uniform(-2.0, 2.0),
        "kind": tune.choice(["bad", "good"]),
    }

    def best(search_alg):
        grid = Tuner(
            _surrogate_objective,
            param_space=space,
            tune_config=TuneConfig(
                metric="score", mode="max", num_samples=36,
                max_concurrent_trials=2,  # sequentiality helps the model
                search_alg=search_alg,
            ),
            resources_per_trial={"CPU": 0.5},
        ).fit()
        return grid.get_best_result().metrics["score"]

    tpe = best(tune.TPESearcher(n_startup_trials=10, seed=5))
    rnd = best(tune.RandomSearch(seed=5))
    assert tpe > rnd, (tpe, rnd)
    assert tpe > 0.35  # near the optimum (0.5 max)


@pytest.mark.slow
def test_concurrency_limiter_bounds_inflight(cluster):
    from ray_tpu import tune
    from ray_tpu.tune import TuneConfig, Tuner

    class Spy(tune.Searcher):
        def __init__(self):
            self.live = 0
            self.max_live = 0
            import random as _r

            self._rng = _r.Random(0)

        def suggest(self, trial_id):
            self.live += 1
            self.max_live = max(self.max_live, self.live)
            return {"x": self._rng.random()}

        def on_trial_complete(self, trial_id, result):
            self.live -= 1

    spy = Spy()
    limited = tune.ConcurrencyLimiter(spy, max_concurrent=2)

    def quick(config):
        from ray_tpu import tune as t

        t.report(score=config["x"])

    Tuner(
        quick,
        param_space={"x": tune.uniform(0, 1)},
        tune_config=TuneConfig(
            metric="score", mode="max", num_samples=8,
            max_concurrent_trials=4, search_alg=limited,
        ),
        resources_per_trial={"CPU": 0.5},
    ).fit()
    assert spy.max_live <= 2, spy.max_live


def test_median_stopping_rule_stops_bad_trials(cluster):
    from ray_tpu import tune
    from ray_tpu.tune import MedianStoppingRule, TuneConfig, Tuner

    def trainable(config):
        import time as _time

        from ray_tpu import tune as t

        for i in range(12):
            # pace the reports so trials' results INTERLEAVE at the
            # controller — an instant trainable dumps all 12 before any
            # peer exists and the median rule has nothing to compare
            _time.sleep(0.15)
            t.report(score=config["level"] + i * 0.01)

    grid = Tuner(
        trainable,
        param_space={"level": tune.grid_search([0.0, 0.1, 1.0, 1.1])},
        tune_config=TuneConfig(
            metric="score",
            mode="max",
            scheduler=MedianStoppingRule(grace_period=4, min_samples_required=2),
            max_concurrent_trials=4,
        ),
        resources_per_trial={"CPU": 0.5},
    ).fit()
    by_level = {r.config["level"]: r for r in grid}
    # the clearly-bad trials stop early; the good ones run to the end
    assert by_level[1.1].status == "TERMINATED"
    stopped = [lvl for lvl, r in by_level.items() if r.status == "STOPPED"]
    assert 0.0 in stopped or 0.1 in stopped, {
        k: (v.status, len(v.metrics_history)) for k, v in by_level.items()
    }


@pytest.mark.slow
def test_logger_callbacks_write_files(cluster, tmp_path):
    from ray_tpu import train, tune
    from ray_tpu.tune import (
        CSVLoggerCallback,
        JSONLoggerCallback,
        TensorBoardLoggerCallback,
        TuneConfig,
        Tuner,
    )

    def trainable(config):
        from ray_tpu import tune as t

        for i in range(3):
            t.report(score=config["x"] * (i + 1), training_iteration=i + 1)

    callbacks = [CSVLoggerCallback(), JSONLoggerCallback()]
    try:
        callbacks.append(TensorBoardLoggerCallback())
        has_tb = True
    except ImportError:
        has_tb = False
    grid = Tuner(
        trainable,
        param_space={"x": tune.grid_search([1.0, 2.0])},
        tune_config=TuneConfig(metric="score", mode="max"),
        resources_per_trial={"CPU": 0.5},
        run_config=train.RunConfig(
            name="logtest", storage_path=str(tmp_path), callbacks=callbacks
        ),
    ).fit()
    import csv as _csv
    import glob
    import json as _json

    exp = tmp_path / "logtest"
    csvs = sorted(glob.glob(str(exp / "*" / "progress.csv")))
    assert len(csvs) == 2
    rows = list(_csv.DictReader(open(csvs[0])))
    assert len(rows) == 3 and "score" in rows[0]
    jsons = sorted(glob.glob(str(exp / "*" / "result.json")))
    assert len(jsons) == 2
    lines = [_json.loads(l) for l in open(jsons[0])]
    assert len(lines) == 3
    assert len(glob.glob(str(exp / "*" / "params.json"))) == 2
    if has_tb:
        events = glob.glob(str(exp / "*" / "events.out.tfevents.*"))
        assert events, "tensorboard events missing"


def test_optuna_adapter_gated():
    from ray_tpu import tune

    try:
        import optuna  # noqa: F401

        has_optuna = True
    except ImportError:
        has_optuna = False
    if has_optuna:
        s = tune.OptunaSearch(seed=0)
        assert s is not None
    else:
        import pytest as _pytest

        with _pytest.raises(ImportError, match="TPESearcher"):
            tune.OptunaSearch(seed=0)
