"""ray_tpu.tune tests: search spaces, ASHA, the controller e2e, and
trainer-as-trainable (reference test model: ``tune/tests/``)."""

import time

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune.schedulers import CONTINUE, STOP


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_generate_variants_grid_product():
    from ray_tpu.tune.search import generate_variants

    vs = generate_variants(
        {"a": tune.grid_search([1, 2, 3]), "b": tune.grid_search(["x", "y"]), "c": 7}
    )
    assert len(vs) == 6
    assert all(v["c"] == 7 for v in vs)
    assert {(v["a"], v["b"]) for v in vs} == {(a, b) for a in (1, 2, 3) for b in ("x", "y")}


def test_generate_variants_samplers_and_num_samples():
    from ray_tpu.tune.search import generate_variants

    vs = generate_variants(
        {"lr": tune.loguniform(1e-4, 1e-1), "bs": tune.choice([16, 32])},
        num_samples=8,
        seed=0,
    )
    assert len(vs) == 8
    assert all(1e-4 <= v["lr"] <= 1e-1 for v in vs)
    assert all(v["bs"] in (16, 32) for v in vs)
    # nested spaces resolve too
    vs2 = generate_variants({"opt": {"lr": tune.uniform(0, 1)}, "k": 3}, seed=1)
    assert 0 <= vs2[0]["opt"]["lr"] <= 1


def test_asha_scheduler_unit():
    """Deterministic ASHA behavior: at a rung, values below the top-1/rf
    cutoff stop."""
    asha = tune.ASHAScheduler(mode="max", max_t=64, grace_period=4, reduction_factor=2)
    assert asha.on_result("a", 4, 100.0) == CONTINUE  # first at rung: no peers
    assert asha.on_result("b", 4, 50.0) == STOP  # below cutoff (100)
    assert asha.on_result("c", 4, 150.0) == CONTINUE  # above
    # min mode flips comparisons
    asha_min = tune.ASHAScheduler(mode="min", max_t=64, grace_period=4, reduction_factor=2)
    assert asha_min.on_result("a", 4, 1.0) == CONTINUE
    assert asha_min.on_result("b", 4, 5.0) == STOP


def _objective(config):
    lr = config["lr"]
    for step in range(1, 16):
        tune.report({"score": lr * step, "step": step})
        time.sleep(0.005)


def test_grid_search_e2e(cluster):
    tuner = tune.Tuner(
        _objective,
        param_space={"lr": tune.grid_search([0.1, 1.0, 5.0, 10.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max", max_concurrent_trials=4),
        resources_per_trial={"CPU": 0.5},
    )
    grid = tuner.fit()
    assert len(grid) == 4
    best = grid.get_best_result()
    assert best.config["lr"] == 10.0
    assert best.metrics["score"] == 10.0 * 15
    assert all(r.status == "TERMINATED" for r in grid)


def test_asha_stops_underperformers_e2e(cluster):
    """8 trials under ASHA: descending lr order guarantees later (worse)
    trials fall below the rung cutoff and are killed early."""
    asha = tune.ASHAScheduler(mode="max", max_t=16, grace_period=2, reduction_factor=2)
    lrs = [16.0, 8.0, 4.0, 2.0, 1.0, 0.5, 0.2, 0.1]
    tuner = tune.Tuner(
        _objective,
        param_space={"lr": tune.grid_search(lrs)},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", scheduler=asha, max_concurrent_trials=2
        ),
        resources_per_trial={"CPU": 0.5},
    )
    grid = tuner.fit()
    assert len(grid) == 8
    stopped = [r for r in grid if r.status == "STOPPED"]
    assert stopped, "ASHA must early-stop underperformers"
    assert grid.get_best_result().config["lr"] == 16.0
    # the best trial ran to completion
    assert next(r for r in grid if r.config["lr"] == 16.0).status == "TERMINATED"


def test_errored_trial_reported(cluster):
    def bad(config):
        if config["x"] == 1:
            raise ValueError("boom")
        tune.report({"ok": 1})

    grid = tune.Tuner(
        bad,
        param_space={"x": tune.grid_search([0, 1])},
        tune_config=tune.TuneConfig(metric="ok", mode="max"),
    ).fit()
    assert len(grid.errors) == 1
    assert "boom" in grid.errors[0].error
    assert grid.get_best_result().config["x"] == 0


def test_trainer_as_trainable(cluster):
    """JaxTrainer launched per-trial: the variant config merges into the
    train loop config (reference train/base_trainer.py:608)."""
    from ray_tpu import train as rt_train
    from ray_tpu.train import JaxBackendConfig, JaxTrainer, RunConfig, ScalingConfig

    def train_fn(config):
        rt_train.report({"loss": 1.0 / config["lr"]})

    trainer = JaxTrainer(
        train_fn,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        backend_config=JaxBackendConfig(distributed=False),
        run_config=RunConfig(name="tune-trial"),
    )
    grid = tune.Tuner(
        trainer,
        param_space={"lr": tune.grid_search([1.0, 2.0, 4.0])},
        tune_config=tune.TuneConfig(metric="loss", mode="min", max_concurrent_trials=1),
    ).fit()
    assert len(grid) == 3
    best = grid.get_best_result()
    assert best.config["lr"] == 4.0
    assert best.metrics["loss"] == 0.25
