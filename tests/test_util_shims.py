"""Ecosystem shims: ActorPool, util.Queue, multiprocessing Pool
(reference ``util/actor_pool.py:13``, ``util/queue.py``,
``util/multiprocessing/pool.py``)."""

import pytest

import ray_tpu
from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.multiprocessing import Pool
from ray_tpu.util.queue import Empty, Queue


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_actor_pool_ordered_and_unordered(cluster):
    @ray_tpu.remote(num_cpus=0.5)
    class W:
        def work(self, x):
            import time

            time.sleep(0.01 * (x % 3))
            return x * 2

    pool = ActorPool([W.remote() for _ in range(2)])
    assert list(pool.map(lambda a, v: a.work.remote(v), range(8))) == [
        v * 2 for v in range(8)
    ]
    out = sorted(pool.map_unordered(lambda a, v: a.work.remote(v), range(8)))
    assert out == [v * 2 for v in range(8)]


def test_queue_fifo_across_workers(cluster):
    q = Queue(maxsize=4)
    q.put("a")
    q.put("b")
    assert q.qsize() == 2
    assert q.get(timeout=10) == "a"

    @ray_tpu.remote(num_cpus=0.5)
    def producer(q):
        for i in range(3):
            q.put(i)
        return True

    assert ray_tpu.get(producer.remote(q), timeout=60)
    got = [q.get(timeout=10) for _ in range(4)]
    assert got == ["b", 0, 1, 2]
    with pytest.raises(Empty):
        q.get_nowait()
    q.shutdown()


def test_multiprocessing_pool(cluster):
    def square(x):
        return x * x

    with Pool(2, ray_remote_args={"num_cpus": 0.5}) as p:
        assert p.map(square, range(10)) == [x * x for x in range(10)]
        assert sorted(p.imap_unordered(square, range(5))) == [0, 1, 4, 9, 16]
        ar = p.apply_async(square, (7,))
        assert ar.get(timeout=60) == 49
        assert p.starmap(lambda a, b: a + b, [(1, 2), (3, 4)]) == [3, 7]
