"""Worker pool lifecycle: idle killing + prestart (reference
``worker_pool.h`` idle-worker reaping / prestart)."""

import time

import pytest

import ray_tpu

def test_idle_worker_killing_and_prestart():
    """The idle_worker_killing_time_s / num_initial_workers flags are
    live: pooled workers above the floor are retired after idling."""
    import time as _t

    from ray_tpu.core.config import GLOBAL_CONFIG

    old_kill = GLOBAL_CONFIG.idle_worker_killing_time_s
    old_init = GLOBAL_CONFIG.num_initial_workers
    GLOBAL_CONFIG.idle_worker_killing_time_s = 1.0
    GLOBAL_CONFIG.num_initial_workers = 1
    try:
        ray_tpu.shutdown()  # a prior test in this module may have left a cluster up
        ray_tpu.init(num_cpus=4)

        @ray_tpu.remote
        def noop():
            return 1

        # spin up several pooled workers
        assert ray_tpu.get([noop.remote() for _ in range(8)], timeout=120) == [1] * 8
        from ray_tpu.core.api import _global_worker

        core = _global_worker().backend
        stats = core.io.run(core.daemon.call("stats"))
        assert stats["num_workers"] >= 2
        deadline = _t.time() + 30
        while _t.time() < deadline:
            stats = core.io.run(core.daemon.call("stats"))
            # retired down to the warm floor (1) + any dedicated workers
            if stats["num_idle"] <= 1:
                break
            _t.sleep(0.5)
        assert stats["num_idle"] <= 1, stats
        # the floor worker still serves tasks
        assert ray_tpu.get(noop.remote(), timeout=60) == 1
    finally:
        GLOBAL_CONFIG.idle_worker_killing_time_s = old_kill
        GLOBAL_CONFIG.num_initial_workers = old_init
        ray_tpu.shutdown()
