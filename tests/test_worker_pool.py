"""Worker pool lifecycle: idle killing + prestart (reference
``worker_pool.h`` idle-worker reaping / prestart).

The two live-cluster tests share ONE module-scoped 4-CPU cluster (the
idle-kill knobs it is booted with don't disturb the OOM test: leased
workers are never idle, and the pool respawns on demand); the later
tests own their clusters / run policy-only."""

import time

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def pool_cluster():
    """4-CPU cluster booted with a 1s idle-kill window and a warm floor
    of one prestarted worker — the knobs land in the spawned daemon via
    the serialized system config, so they must be set before init."""
    from ray_tpu.core.config import GLOBAL_CONFIG

    old_kill = GLOBAL_CONFIG.idle_worker_killing_time_s
    old_init = GLOBAL_CONFIG.num_initial_workers
    GLOBAL_CONFIG.idle_worker_killing_time_s = 1.0
    GLOBAL_CONFIG.num_initial_workers = 1
    try:
        ray_tpu.shutdown()  # an earlier module may have left a cluster up
        ray_tpu.init(num_cpus=4)
        yield
    finally:
        GLOBAL_CONFIG.idle_worker_killing_time_s = old_kill
        GLOBAL_CONFIG.num_initial_workers = old_init
        ray_tpu.shutdown()


def test_idle_worker_killing_and_prestart(pool_cluster):
    """The idle_worker_killing_time_s / num_initial_workers flags are
    live: pooled workers above the floor are retired after idling."""
    import time as _t

    @ray_tpu.remote
    def noop():
        return 1

    # spin up several pooled workers
    assert ray_tpu.get([noop.remote() for _ in range(8)], timeout=120) == [1] * 8
    from ray_tpu.core.api import _global_worker

    core = _global_worker().backend
    stats = core.io.run(core.daemon.call("stats"))
    assert stats["num_workers"] >= 2
    deadline = _t.time() + 30
    while _t.time() < deadline:
        stats = core.io.run(core.daemon.call("stats"))
        # retired down to the warm floor (1) + any dedicated workers
        if stats["num_idle"] <= 1:
            break
        _t.sleep(0.5)
    assert stats["num_idle"] <= 1, stats
    # the floor worker still serves tasks
    assert ray_tpu.get(noop.remote(), timeout=60) == 1


def test_oom_killer_picks_newest_leased_worker(pool_cluster):
    """Memory-monitor policy (reference WorkerKillingPolicy): under
    memory pressure the NEWEST leased task worker dies; actors and idle
    workers are spared. Uses an injected availability reading."""
    import time as _t

    @ray_tpu.remote(num_cpus=1, max_retries=2)
    def hold(tag):
        _t.sleep(6)
        return tag

    refs = [hold.remote(i) for i in range(2)]
    _t.sleep(2.0)  # both leased and running

    # reach into the head daemon (in-process would be cleaner, but the
    # daemon runs in the head subprocess) — drive the policy via the
    # same code path on a locally-constructed state instead:
    from ray_tpu.core.node_daemon import Lease, NodeDaemon, WorkerProc

    class FakeProc:
        def __init__(self):
            self.killed = False
        def kill(self):
            self.killed = True
        def poll(self):
            return None

    d = NodeDaemon.__new__(NodeDaemon)  # policy-only instance
    d.leases = {}
    w1, w2 = WorkerProc(1, FakeProc(), "a"), WorkerProc(2, FakeProc(), "b")
    actor_w = WorkerProc(3, FakeProc(), "c")
    actor_w.actor_id = object()
    d.leases[1] = Lease(1, {"CPU": 1}, w1)
    d.leases[2] = Lease(2, {"CPU": 1}, w2)
    d.leases[3] = Lease(3, {"CPU": 1}, actor_w)

    assert d._oom_check(available_fraction=0.5) is None  # healthy
    victim = d._oom_check(available_fraction=0.001)
    assert victim is w2  # newest non-actor lease
    assert w2.proc.killed and not w1.proc.killed and not actor_w.proc.killed

    # the real cluster's tasks still complete (retries cover any kill)
    assert ray_tpu.get(refs, timeout=120) == [0, 1]


def test_blocked_worker_releases_cpu_for_nested_task():
    """The classic nested-task deadlock (README "Known gaps", now fixed):
    on a 1-CPU cluster a parent task that blocks in ray.get on a child
    that ALSO needs 1 CPU can only complete if the blocked parent's CPU
    is lent out for the duration — the reference frees a blocked
    worker's resources during sync get/arg-fetch and re-acquires on
    wake. Without the release this parks forever."""
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=1)
    try:

        @ray_tpu.remote(num_cpus=1)
        def child():
            return 7

        @ray_tpu.remote(num_cpus=1)
        def parent():
            return ray_tpu.get(child.remote(), timeout=90) + 1

        assert ray_tpu.get(parent.remote(), timeout=120) == 8
    finally:
        ray_tpu.shutdown()


def test_blocked_release_accounting_balances():
    """Daemon-side accounting property: block releases the CPU share to
    the pool, unblock re-acquires; a lease released while the debt is
    outstanding withholds exactly the released amount — available never
    exceeds total and never leaks."""
    import asyncio

    from ray_tpu.core.node_daemon import Lease, NodeDaemon, WorkerProc
    from ray_tpu.core.resources import NodeResources, ResourceSet

    class FakeProc:
        def poll(self):
            return None

    d = NodeDaemon.__new__(NodeDaemon)  # policy-only instance
    d.resources = NodeResources(ResourceSet({"CPU": 2.0}))
    d.workers = {}
    d.leases = {}
    d._bundle_pools = {}
    d._capacity_event = asyncio.Event()
    w = WorkerProc(1, FakeProc(), "tok-a")
    d.workers["tok-a"] = w
    d.resources.allocate(ResourceSet({"CPU": 2.0}))
    d.leases[1] = Lease(1, {"CPU": 2.0}, w)

    async def run():
        assert d.resources.available.get("CPU") == 0.0
        # block: the lease's CPUs go back to the pool
        assert await d.d_worker_blocked({"token": "tok-a"}, None) is True
        assert d.resources.available.get("CPU") == 2.0
        # idempotent while already blocked
        assert await d.d_worker_blocked({"token": "tok-a"}, None) is False
        # another task takes 1.5 CPUs meanwhile
        d.resources.allocate(ResourceSet({"CPU": 1.5}))
        # wake: 2.0 don't fit (only 0.5 free) -> stays lent (oversubscribed)
        assert await d.d_worker_unblocked({"token": "tok-a"}, None) is False
        # lease release withholds the lent CPUs: available must end at
        # exactly total - other task's 1.5, with no double release
        d._release_lease(1)
        assert d.resources.available.get("CPU") == 0.5
        assert w.blocked_released is None
        # the other task finishes: pool returns to full, not beyond
        d.resources.release(ResourceSet({"CPU": 1.5}))
        assert d.resources.available.get("CPU") == 2.0
        # unknown workers / not-blocked workers are no-ops
        assert await d.d_worker_unblocked({"token": "tok-a"}, None) is False
        assert await d.d_worker_blocked({"token": "nope"}, None) is False

    asyncio.get_event_loop_policy().new_event_loop().run_until_complete(run())
